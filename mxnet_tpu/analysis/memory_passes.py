"""Memory passes: activation liveness, remat opportunities, HBM budget.

The ROADMAP MFU campaign's first lever is memory — batch sizes that
saturate the chip only fit if activations do (item 3a) — and the graph
already tells us statically which activations are worth rematerializing.
Three passes on the PR 3 liveness machinery:

* ``remat-opportunity`` (graph pass, INFO) — rank **long-lived,
  cheap-to-recompute** activations: bytes that must be held from the
  forward until the backward revisits them, against the FLOPs it would
  cost to recompute them from their inputs. The report
  (``Report.extras["remat"]``) carries concrete ``jax.checkpoint``
  policy suggestions ("wrap each repeated block, policy X") whose effect
  is *measurable* through :func:`analyze_program_memory` — the
  acceptance test applies the top suggestion and asserts the analyzed
  peak drops.
* ``hbm-budget`` (graph pass, ERROR) — an enforceable per-device memory
  budget (``MXNET_TPU_ANALYZE_HBM_BUDGET``, e.g. ``16G``): when the
  static peak estimate (bound buffers + activation high-water) exceeds
  it, the finding names the offending arrays and ``strict`` mode rejects
  the bind **before any trace or compile** — on a 6000-chip job the OOM
  bill arrives at bind time, not after the first step.
* :func:`analyze_program_memory` (program-level) — hierarchical jaxpr
  liveness: walk the eqns of a traced program (descending into
  pjit/remat/scan bodies, whose temporaries spike transiently during the
  call) and report the activation high-water plus the largest values
  live at the peak. This is the program twin of the graph cost model's
  ``peak_bytes`` and the metric the remat suggestions move.

The budget knob is parsed with K/M/G/T suffixes (:func:`parse_bytes`).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

import numpy as np

from .findings import Report, Severity
from .graph_passes import GraphContext, _nelem, _node_flops, graph_pass

__all__ = ["analyze_program_memory", "parse_bytes", "check_reservation",
           "REMAT_CHEAP_FLOPS_PER_BYTE", "REMAT_TOP_N"]

# recompute cost ceiling for a "cheap" activation: recomputing must cost
# no more than this many FLOPs per byte saved (elementwise/norm/softmax
# chains are ~0.25-8; contractions are 2*K/itemsize and land here only
# for tiny K)
REMAT_CHEAP_FLOPS_PER_BYTE = 16.0
# candidates surfaced as findings (the full ranked list rides in extras)
REMAT_TOP_N = 5
# activations smaller than this are not worth a finding (bytes)
REMAT_MIN_BYTES = 4096

# ops whose outputs a dot-saveable policy would still SAVE (contraction
# outputs); when these dominate the candidate list only the per-block
# nothing_saveable form recovers the bytes
_CONTRACTION_OPS = {"FullyConnected", "dot", "batch_dot", "linalg_gemm2",
                    "Convolution", "Convolution_v1", "Deconvolution"}


def parse_bytes(spec) -> int:
    """``"16G"``/``"16GB"``/``"512MiB"``/``"1.5T"``/plain ints -> bytes
    (0 = unset). Raises ``ValueError`` naming the accepted grammar on
    garbage — callers on the bind path degrade to a finding instead of
    crashing the bind."""
    if spec is None:
        return 0
    s = str(spec).strip()
    if not s:
        return 0
    mult = 1
    m = re.match(r"^([0-9.eE+-]+)\s*([KMGT])(I?B)?$", s, re.IGNORECASE)
    if m:
        mult = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30,
                "T": 1 << 40}[m.group(2).upper()]
        s = m.group(1)
    try:
        val = int(float(s) * mult)
    except ValueError:
        raise ValueError(
            "cannot parse byte size %r (expected a number with an "
            "optional K/M/G/T[B|iB] suffix, e.g. '16G')" % (spec,))
    if val < 0:
        # a stray minus must not silently disable budget enforcement
        raise ValueError("byte size %r is negative" % (spec,))
    return val


# ------------------------------------------------------- remat opportunity


@graph_pass("remat-opportunity")
def remat_pass(ctx: GraphContext, report: Report) -> None:
    """Rank activations by bytes-held-until-backward vs recompute FLOPs.

    In a training bind every forward intermediate is a residual: it is
    produced at topo position p and must survive until the backward pass
    revisits it — the earlier it is produced, the longer it occupies HBM.
    An activation is a remat candidate when recomputing it from its own
    inputs is cheap (``REMAT_CHEAP_FLOPS_PER_BYTE``). The emitted
    suggestion is a concrete ``jax.checkpoint`` policy:

    * candidates dominated by contraction outputs (matmul/conv) need the
      per-block ``nothing_saveable`` form — a dots-saveable policy would
      keep exactly the bytes we want back;
    * elementwise/norm/softmax-dominated candidates are recovered by
      ``dots_with_no_batch_dims_saveable`` (keep matmuls, recompute the
      cheap tail) — the policy the fused step's
      ``MXNET_EXEC_ENABLE_REMAT`` knob already applies.
    """
    if ctx.has_cycle or not ctx.shapes:
        return
    n_nodes = len(ctx.nodes)
    order = {id(n): i for i, n in enumerate(ctx.nodes)}
    candidates: List[Dict[str, Any]] = []
    for node in ctx.nodes:
        if node.is_variable:
            continue
        in_avals = [ctx.shapes.get((id(src), i)) for src, i in node.inputs]
        out_avals = []
        i = 0
        while (id(node), i) in ctx.shapes:
            out_avals.append(ctx.shapes[(id(node), i)])
            i += 1
        if not out_avals or any(a is None for a in in_avals):
            continue
        out_bytes = sum(_nelem(s) * dt.itemsize for s, dt in out_avals)
        if out_bytes < REMAT_MIN_BYTES:
            continue
        recompute = _node_flops(node, in_avals, out_avals)
        flops_per_byte = recompute / float(out_bytes)
        if flops_per_byte > REMAT_CHEAP_FLOPS_PER_BYTE:
            continue
        # residual lifetime: from production to the end of the forward
        # (the backward walks the graph in reverse, so an activation
        # produced at p is held for ~(n_nodes - p) of the program)
        span = n_nodes - order[id(node)]
        candidates.append({
            "node": node.name, "op": node.op.name,
            "bytes": int(out_bytes), "recompute_flops": int(recompute),
            "flops_per_byte": round(flops_per_byte, 3),
            "live_span": int(span),
            "shape": [list(s) for s, _ in out_avals],
        })
    candidates.sort(key=lambda c: (-c["bytes"], -c["live_span"]))
    if not candidates:
        return
    top = candidates[:REMAT_TOP_N]
    total_bytes = sum(c["bytes"] for c in candidates)
    # bytes-dominance, as documented: only when contraction outputs hold
    # the majority of the recoverable top-N bytes is the aggressive
    # per-block nothing_saveable worth it — a dots-saveable policy would
    # keep exactly those bytes. Otherwise keep the matmuls and recompute
    # the cheap elementwise/norm tail.
    top_bytes = sum(c["bytes"] for c in top) or 1
    contraction_bytes = sum(c["bytes"] for c in top
                            if c["op"] in _CONTRACTION_OPS)
    policy = "nothing_saveable" if contraction_bytes * 2 > top_bytes \
        else "dots_with_no_batch_dims_saveable"
    suggestion = {
        "policy": policy,
        "wrap": "repeated_block",
        "hint": "wrap each repeated block (layer) in jax.checkpoint("
                "block, policy=jax.checkpoint_policies.%s); verify with "
                "analysis.analyze_program_memory on the grad program"
                % policy,
        "est_bytes_saved": int(total_bytes),
    }
    # calibrated peak prediction: when the graph has a verified repeated
    # chain (the scan-over-layers detector), measure ONE block's actual
    # vjp residuals with and without the policy and scale by depth —
    # the number MXNET_TPU_REMAT=auto is held to (round-trip test:
    # applied remat must move analyze_program_memory's high-water by
    # this amount ±25%)
    est_peak = _predict_block_savings(ctx, policy)
    if est_peak is not None:
        suggestion["est_peak_saving"] = int(est_peak)
    report.extras["remat"] = {"candidates": candidates,
                              "suggestion": suggestion}
    for c in top:
        report.add(
            "remat-opportunity", Severity.INFO,
            "%s output (%s, %.3g MB) is held from topo position %d to the "
            "backward but costs only %.3g FLOP/byte to recompute — "
            "rematerialize it (suggested policy: %s)"
            % (c["op"], "x".join(map(str, c["shape"][0])), c["bytes"] / 1e6,
               n_nodes - c["live_span"], c["flops_per_byte"], policy),
            node=c["node"], op=c["op"], detail=c)


def _predict_block_savings(ctx: GraphContext, policy_name: str):
    """Predicted activation-high-water drop of applying ``policy_name``
    per repeated block: detect the chain (scan-over-layers machinery),
    build ONE block as a callable over zeros of the bound shapes, and
    compare the byte size of its actual ``jax.vjp`` residuals plain vs
    checkpointed — scaled by the layer count. Values don't matter
    (residual SIZES are shape-determined), so zeros suffice; one block's
    forward+vjp trace is comparable to the shape pass's cost. Returns
    None when no verified chain exists or anything fails.

    Gated: a plain ``warn``/``strict`` bind analysis must not execute
    compute (the bind contract is static-only), so the calibration runs
    only when an applied-remat knob is active — the consumer of the
    number — or when the caller forces it (the audit CLI, the
    round-trip test) via ``analyze_symbol(calibrate_remat=True)``."""
    want = getattr(ctx, "calibrate_remat", None)
    if want is None:
        from .. import config as _config
        want = _config.get("MXNET_TPU_REMAT") != "off" or \
            bool(_config.get("MXNET_EXEC_ENABLE_REMAT"))
    if not want:
        return None
    try:
        from ..symbol.scan import build_scan_plan
        plan = build_scan_plan(ctx.sym, min_repeat=2)
        if plan is None:
            return None
        import jax
        import jax.numpy as jnp
        from ..executor import _run_node

        def zeros(key):
            aval = ctx.shapes.get(key)
            if aval is None:
                raise KeyError(key)
            shape, dt = aval
            return jnp.zeros(shape, dt)

        stream_key = (id(plan.stream_in[0]), plan.stream_in[1])
        x0 = zeros(stream_key)
        pvals = {tid: zeros((tid, 0)) for tid in plan.var_lists}
        out_key = (plan.layer_table[0][plan._out_pos()], plan.out_idx)
        rng = jax.random.PRNGKey(0)
        shared_cache: Dict[Tuple[int, int], Any] = {}

        def block_fn(x, pv):
            seg: Dict[Tuple[int, int], Any] = {}

            def entry_val(ent):
                node, ei = ent
                k = (id(node), ei)
                if k == stream_key:
                    return x
                if k in seg:
                    return seg[k]
                if id(node) in pv:
                    return pv[id(node)]
                if k not in shared_cache:
                    shared_cache[k] = zeros(k)
                return shared_cache[k]

            for node in plan.template:
                ins = [entry_val(e) for e in node.inputs]
                outs = _run_node(node, ins, rng, 0, True)
                for i, o in enumerate(outs):
                    seg[(id(node), i)] = o
            return seg[out_key]

        def residual_bytes(fn):
            _, f_vjp = jax.vjp(fn, x0, pvals)
            return sum(getattr(leaf, "nbytes", 0)
                       for leaf in jax.tree_util.tree_leaves(f_vjp))

        policy = getattr(jax.checkpoint_policies, policy_name)
        plain = residual_bytes(block_fn)
        kept = residual_bytes(jax.checkpoint(block_fn, policy=policy))
        return plan.n_layers * max(0, plain - kept)
    except Exception:                                       # noqa: BLE001
        return None


# ------------------------------------------------------------- HBM budget


@graph_pass("hbm-budget")
def budget_pass(ctx: GraphContext, report: Report) -> None:
    """Reject binds whose static peak estimate cannot fit the budget.

    Reads ``Report.extras["cost"]`` (the cost-model pass runs first) and
    the ``MXNET_TPU_ANALYZE_HBM_BUDGET`` knob; the ERROR finding names
    the offending arrays — the largest bound buffers and the activations
    live at the high-water point — so the fix (shard it, remat it,
    shrink the batch) is actionable from the message alone.
    """
    from .. import config as _config
    raw = _config.get("MXNET_TPU_ANALYZE_HBM_BUDGET")
    try:
        budget = parse_bytes(raw)
    except ValueError as exc:
        # a config typo must not brick every bind in warn mode: degrade
        # to a finding that names the knob (strict mode still proceeds —
        # WARNING, not ERROR, because no memory claim was established)
        report.add(
            "hbm-budget", Severity.WARNING,
            "MXNET_TPU_ANALYZE_HBM_BUDGET=%r is unparseable (%s) — the "
            "memory budget is NOT being enforced" % (raw, exc))
        return
    if budget <= 0:
        return
    cost = report.extras.get("cost")
    if not cost:
        return
    peak = int(cost.get("peak_bytes") or 0)
    if peak <= budget:
        report.extras["hbm_budget"] = {"budget_bytes": budget,
                                       "peak_bytes": peak, "fits": True}
        return
    # name the offenders: biggest bound variables + biggest activations
    offenders: List[Tuple[str, str, int]] = []
    for node in ctx.nodes:
        aval = ctx.shapes.get((id(node), 0)) if node.is_variable else None
        if aval is not None:
            offenders.append((node.name, "bound", _nelem(aval[0])
                              * aval[1].itemsize))
    for rec in cost.get("top_nodes", ()):
        offenders.append((rec["node"], "op bytes-moved", int(rec["bytes"])))
    offenders.sort(key=lambda r: -r[2])
    offenders = offenders[:6]
    named = ", ".join("%s (%s, %.3g MB)" % (n, kind, b / 1e6)
                      for n, kind, b in offenders)
    report.extras["hbm_budget"] = {
        "budget_bytes": budget, "peak_bytes": peak, "fits": False,
        "offenders": [{"name": n, "kind": k, "bytes": b}
                      for n, k, b in offenders]}
    report.add(
        "hbm-budget", Severity.ERROR,
        "estimated peak memory %.3g MB exceeds MXNET_TPU_ANALYZE_HBM_BUDGET"
        " %.3g MB — largest contributors: %s (shard/remat them or shrink "
        "the batch; strict mode rejects this bind before any compile)"
        % (peak / 1e6, budget / 1e6, named),
        detail={"budget_bytes": budget, "peak_bytes": peak})


def check_reservation(name: str, nbytes: int,
                      detail: str = "") -> Dict[str, Any]:
    """Audit a long-lived device reservation (the serve KV cache) against
    ``MXNET_TPU_ANALYZE_HBM_BUDGET`` — the runtime twin of the bind-time
    ``hbm-budget`` pass for memory claimed OUTSIDE a graph bind.

    Returns ``{"budget_bytes", "reserved_bytes", "fits"}`` (budget 0 =
    unset, always fits). Over budget: ``MXNET_TPU_ANALYZE=strict`` raises
    :class:`~mxnet_tpu.base.MXNetError` NAMING the reservation before any
    device allocation; ``warn`` logs a WARNING with the same message.
    Callers gate the import of this module on the analyze knob, so the
    analyzer stays unimported when analysis is off.
    """
    import logging
    from .. import config as _config
    from ..base import MXNetError
    raw = _config.get("MXNET_TPU_ANALYZE_HBM_BUDGET")
    try:
        budget = parse_bytes(raw)
    except ValueError as exc:
        logging.getLogger(__name__).warning(
            "MXNET_TPU_ANALYZE_HBM_BUDGET=%r is unparseable (%s) — "
            "reservation %r is NOT being audited", raw, exc, name)
        return {"budget_bytes": 0, "reserved_bytes": int(nbytes),
                "fits": True}
    out = {"budget_bytes": budget, "reserved_bytes": int(nbytes),
           "fits": budget <= 0 or int(nbytes) <= budget}
    if out["fits"]:
        return out
    msg = ("reservation %r (%s%.3g MB) exceeds MXNET_TPU_ANALYZE_HBM_BUDGET"
           " %.3g MB — shrink max_sequences / the decode bucket set, or "
           "enable MXNET_TPU_SERVE_KV_INT8"
           % (name, (detail + ", ") if detail else "",
              nbytes / 1e6, budget / 1e6))
    if _config.get("MXNET_TPU_ANALYZE") == "strict":
        raise MXNetError("hbm-budget: " + msg)
    logging.getLogger(__name__).warning("hbm-budget: %s", msg)
    return out


# ------------------------------------------------- program-level liveness


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    if aval is None or shape is None:
        return 0
    try:
        return int(np.prod(shape, dtype=np.int64)
                   * np.dtype(aval.dtype).itemsize) if shape \
            else int(np.dtype(aval.dtype).itemsize)
    except Exception:                                       # noqa: BLE001
        return 0


def _sub_jaxprs(eqn):
    from jax._src import core as _core
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            if isinstance(x, _core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, _core.Jaxpr):
                yield x


def _jaxpr_peak(jaxpr, depth: int = 0) -> Tuple[int, List[Dict[str, Any]]]:
    """Hierarchical liveness high-water of one jaxpr's *intermediates*
    (invars excluded — those are the caller's buffers). Sub-jaxpr bodies
    (pjit/remat/scan/cond) contribute transiently: the high-water
    considers ``live_at_call + sub_peak``, which is exactly how a remat
    body's recompute spike behaves at runtime. Returns (peak_bytes,
    live-set snapshot at the peak)."""
    if depth > 16:
        return 0, []
    last: Dict[int, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            last[id(v)] = i
    for v in jaxpr.outvars:
        last[id(v)] = len(jaxpr.eqns)
    live = 0
    peak = 0
    alive: Dict[int, Tuple[int, str]] = {}
    at_peak: List[Dict[str, Any]] = []

    def snapshot(extra=None):
        rows = sorted(alive.values(), key=lambda r: -r[0])[:5]
        rows = [{"bytes": b, "value": s} for b, s in rows]
        if extra:
            rows.insert(0, extra)
        return rows

    for i, eqn in enumerate(jaxpr.eqns):
        sub_peak = 0
        sub_rows: List[Dict[str, Any]] = []
        for sub in _sub_jaxprs(eqn):
            p, rows = _jaxpr_peak(sub, depth + 1)
            if p > sub_peak:
                sub_peak, sub_rows = p, rows
        if live + sub_peak > peak:
            peak = live + sub_peak
            at_peak = snapshot({"bytes": sub_peak,
                                "value": "%s body (transient)"
                                         % eqn.primitive.name})
        for v in eqn.outvars:
            b = _aval_bytes(v)
            live += b
            aval = getattr(v, "aval", None)
            alive[id(v)] = (b, "%s -> %s%s" % (
                eqn.primitive.name,
                getattr(aval, "dtype", "?"),
                list(getattr(aval, "shape", ()))))
        if live > peak:
            peak = live
            at_peak = snapshot()
        for vid in {id(v) for v in eqn.invars}:
            if last.get(vid) == i and vid in alive:
                live -= alive.pop(vid)[0]
        for v in eqn.outvars:
            # outputs nothing ever consumes (DropVars, unused tuple
            # elements) die right after the peak check — leaving them
            # "live" to the end would inflate every later point
            if id(v) not in last and id(v) in alive:
                live -= alive.pop(id(v))[0]
    return peak, at_peak


def analyze_program_memory(fn, *args, context: str = "program-memory",
                           **kwargs) -> Report:
    """Trace ``fn(*args, **kwargs)`` and report its activation
    high-water via hierarchical jaxpr liveness.

    ``fn`` may be a plain/jitted function or an already-made
    ``ClosedJaxpr``. ``Report.extras["program_memory"]`` carries
    ``activation_peak_bytes`` (intermediates only), ``arg_bytes`` (the
    caller's input buffers), ``peak_bytes`` (their sum — comparable to
    the graph cost model's), and ``top_live`` — the largest values alive
    at the peak, named by producing primitive. This is the measurement
    the remat suggestions move: analyze the grad program plain and with
    the suggested per-block ``jax.checkpoint`` policy and compare.
    """
    import jax
    from jax._src import core as _core

    report = Report(context=context)
    if isinstance(fn, _core.ClosedJaxpr):
        closed = fn
    else:
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
    from .program_passes import _unwrap_pjit
    main = _unwrap_pjit(closed)
    peak, top_live = _jaxpr_peak(main.jaxpr)
    arg_bytes = sum(_aval_bytes(v) for v in main.jaxpr.invars)
    const_bytes = sum(_aval_bytes(v) for v in main.jaxpr.constvars)
    mem = {
        "activation_peak_bytes": int(peak),
        "arg_bytes": int(arg_bytes),
        "const_bytes": int(const_bytes),
        "peak_bytes": int(peak + arg_bytes + const_bytes),
        "n_eqns": len(main.jaxpr.eqns),
        "top_live": top_live,
    }
    report.extras["program_memory"] = mem
    report.add(
        "program-memory", Severity.INFO,
        "activation high-water %.3g MB over %d eqns (+%.3g MB args); "
        "largest at peak: %s"
        % (peak / 1e6, mem["n_eqns"], arg_bytes / 1e6,
           ", ".join("%s (%.3g MB)" % (r["value"], r["bytes"] / 1e6)
                     for r in top_live[:3]) or "n/a"),
        detail=mem)
    return report

"""Machine-readable candidate lists for the autotuner (ISSUE 19).

The analyzer's passes report *findings* — prose for humans plus
``Report.extras`` for tools. :mod:`mxnet_tpu.tune`'s static pruner needs
the extras shaped as *ranked candidate lists* it can iterate, score and
reject without parsing messages. This module is that adapter layer: pure
functions over the existing cost/remat/comm models, no new estimators.

* :func:`cost_report` — one analyzer run per (symbol, shapes,
  grad_accum) with the cost + memory passes, remat calibration on.
* :func:`peak_bytes` / :func:`remat_candidates` — the pruner's inputs:
  the static HBM high-water and the ordered remat policy ladder with
  calibrated ``est_peak_saving``.
* :func:`rank_layouts` — every ``data x fsdp x tp`` factorization of the
  device count, ranked by analytic per-device collective bytes
  (:func:`~.sharding_passes.comm_link_bytes` ring counts — the same
  model the HLO collective walk prices with) with a per-device memory
  estimate for the budget check.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .findings import Report
from .graph_passes import analyze_symbol
from .sharding_passes import comm_link_bytes

__all__ = ["cost_report", "peak_bytes", "remat_candidates",
           "rank_layouts"]

# optimizer state multiplier for the per-device memory estimate: params
# + gradient + the two Adam-class moments (SGD carries less — this is a
# budget check, so the conservative bound is the useful one)
_PARAM_STATE_MULT = 4


def cost_report(sym, input_shapes, input_dtypes=None, grad_accum=1,
                batch_inputs=None) -> Report:
    """One static analysis of ``sym`` at the given microbatching factor:
    cost model (microbatch-aware liveness), remat opportunity with
    calibration forced on (the tuner needs ``est_peak_saving`` to order
    remat candidates even when no remat knob is set), and hbm-budget."""
    return analyze_symbol(
        sym, input_shapes=input_shapes, input_dtypes=input_dtypes,
        passes=("shape-error", "cost-model", "remat-opportunity",
                "hbm-budget"),
        context="tune", calibrate_remat=True, grad_accum=grad_accum,
        batch_inputs=batch_inputs)


def peak_bytes(report: Report) -> Optional[int]:
    """The static per-device HBM high-water (bound buffers + activation
    peak) the hbm-budget pass enforces; None when shapes were too
    partial to price."""
    cost = report.extras.get("cost")
    if not cost or not cost.get("peak_bytes"):
        return None
    return int(cost["peak_bytes"])


def remat_candidates(report: Report) -> List[Dict[str, Any]]:
    """The remat policy ladder for this graph, strongest saving first:
    ``[{"policy", "est_peak_saving", "est_bytes_saved", "wrap"}, ...]``
    plus the implicit ``{"policy": "off"}`` entry (always first — remat
    costs recompute FLOPs, so "off" is the default until memory forces a
    rung down the ladder)."""
    out: List[Dict[str, Any]] = [
        {"policy": "off", "est_peak_saving": 0, "est_bytes_saved": 0,
         "wrap": None}]
    remat = report.extras.get("remat") or {}
    sug = remat.get("suggestion")
    if sug and sug.get("policy"):
        out.append({
            "policy": str(sug["policy"]),
            "est_peak_saving": int(sug.get("est_peak_saving") or 0),
            "est_bytes_saved": int(sug.get("est_bytes_saved") or 0),
            "wrap": sug.get("wrap"),
        })
    return out


def _factorizations(n: int) -> List[tuple]:
    out = []
    for fsdp in range(1, n + 1):
        if n % fsdp:
            continue
        rest = n // fsdp
        for tp in range(1, rest + 1):
            if rest % tp:
                continue
            out.append((rest // tp, fsdp, tp))
    return out


def rank_layouts(n_devices: int, param_bytes: int,
                 activation_bytes: int,
                 max_tp: Optional[int] = None) -> List[Dict[str, Any]]:
    """Every ``(data, fsdp, tp)`` factorization of ``n_devices``, ranked
    by analytic per-device collective bytes per step:

    * data axis — ring all-reduce of the gradients across ``data``;
    * fsdp axis — all-gather of the parameters (forward) plus
      reduce-scatter of the gradients across ``fsdp``;
    * tp axis — per-layer activation all-reduces, priced on the
      activation high-water as the proxy buffer.

    Each record carries ``mem_bytes``: the per-device resident estimate
    (params + grads + optimizer moments sharded over ``fsdp x tp``,
    activations sharded over the batch axes) the pruner checks against
    the HBM budget. Ties (and the ranking itself) are deterministic:
    sorted by (comm_bytes, mem_bytes, -data)."""
    recs = []
    for data, fsdp, tp in _factorizations(max(1, int(n_devices))):
        if max_tp is not None and tp > max_tp:
            continue
        comm = (comm_link_bytes("all-reduce", param_bytes, data)
                + comm_link_bytes("all-gather", param_bytes, fsdp)
                + comm_link_bytes("reduce-scatter", param_bytes, fsdp)
                + comm_link_bytes("all-reduce", activation_bytes, tp))
        mem = (param_bytes * _PARAM_STATE_MULT) // max(1, fsdp * tp) \
            + activation_bytes // max(1, data * fsdp)
        recs.append({"data": data, "fsdp": fsdp, "tp": tp,
                     "comm_bytes": int(comm), "mem_bytes": int(mem)})
    recs.sort(key=lambda r: (r["comm_bytes"], r["mem_bytes"],
                             -r["data"]))
    return recs

"""Whole-program lock-order analysis — the static half of the
concurrency verifier (runtime half: ``mxnet_tpu.lockcheck``).

The PR 3 linter is *lexical*: it sees a host sync under a ``with lock:``
only when both are in one function. Every deadlock this codebase
actually shipped crossed a function or thread boundary — the PR 2
train_rcnn cycle hid the sync one helper call down, the PR 13
flush-ordering race spanned two modules. This pass closes that gap with
the classic static lockset construction (Eraser's discipline applied to
an AST): name every lock object in the package, walk every function with
a held-set, resolve calls ONE level through package-local helpers, and
check the resulting acquires-while-holding graph.

Graph model
-----------
*Nodes* are named lock objects:

* module globals assigned a ``threading``/``lockcheck`` factory call
  (``_ring_lock = threading.Lock()``) — ``<module>.<name>``;
* instance attributes assigned one in any method (``self._lock =
  lockcheck.Lock(...)``) — ``<module>.<Class>.<attr>``; a
  ``Condition(self.other)`` aliases to the lock it shares; a
  list-comprehension of factory calls names the COLLECTION
  (``<...>.<attr>[]`` — its members are one node, matching the runtime
  witness's creation-site keying);
* lock-named expressions the tables can't resolve get a node scoped to
  their function — they still participate locally but never unify
  across functions (no false cycles from guessing).

Receivers other than ``self`` resolve through two tables: a module
function registered as a ``Thread(target=...)`` from class ``C``
resolves ``srv._lock``-style attrs against ``C`` (the scheduler-loop
idiom), and an attr defined by exactly one class in the program resolves
globally.

*Edges* ``A -> B`` mean "B acquired while A held", from three sources:
``with``-nesting, bare ``acquire()``/``release()`` pair tracking, and —
the interprocedural step — a call made while holding ``A`` into a
package-local helper that acquires ``B``.

Findings
--------
* ``lock-order-cycle`` (ERROR): a cycle in the edge graph; the message
  names every edge's acquisition chain with file:line. Two threads
  driving any two edges of the cycle concurrently can deadlock.
* ``lock-host-sync`` (ERROR, interprocedural upgrade): a call made while
  holding a lock into a helper whose body host-syncs (``asnumpy`` et
  al.) — exactly the depth-1 shape the lexical pass cannot see. Depth-0
  syncs stay the lexical linter's job (never double-reported here).
* ``unlocked-shared-state`` (WARNING): an instance attribute written
  under a lock in one method but written with NO lock held on a
  thread-entry path (a ``Thread(target=...)`` function or a helper it
  calls) — the lock discipline exists but has a hole. ``__init__``
  writes are exempt (``Thread.start()`` is the happens-before edge).

Suppression uses the shared ``# mx-lint: allow(<code>)`` machinery: a
finding is dropped when any line materially involved (the acquisition
lines of a cycle's edges, the call line / callee sync line of an
interprocedural sync, the unlocked write line) carries the annotation.
Findings flow through the ordinary :class:`Report`, so the baseline and
CI drift gates of ``python -m mxnet_tpu.analysis lint`` apply unchanged.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Report, Severity
from .lint import _ALLOW, _HOST_SYNC_METHODS, _LOCK_NAME, _dotted

__all__ = ["analyze_sources"]

_FACTORY_LEAVES = {"Lock", "RLock", "Condition"}
_FACTORY_ROOTS = {"threading", "_threading", "lockcheck", "_lockcheck",
                  "mx", "mxnet_tpu"}
_SYNC_FULL = {"jax.block_until_ready", "jax.device_get"}
_THREAD_LEAVES = {"Thread"}


def _is_factory(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    d = _dotted(call.func)
    if not d:
        return False
    leaf = d.rsplit(".", 1)[-1]
    if leaf not in _FACTORY_LEAVES:
        return False
    if "." not in d:
        return True                      # `from threading import Lock`
    return d.split(".", 1)[0] in _FACTORY_ROOTS


def _lockish(node: ast.AST) -> str:
    """Dotted rendering that also unwraps one trailing subscript
    (``self._iter_locks[i]`` -> ``self._iter_locks[]``)."""
    if isinstance(node, ast.Subscript):
        base = _lockish(node.value)
        return base + "[]" if base else ""
    return _dotted(node)


class _Event:
    __slots__ = ("kind", "line", "name", "held", "allow_lines")

    def __init__(self, kind, line, name, held=(), allow_lines=()):
        self.kind = kind          # "acquire" | "sync" | "call" | "write"
        self.line = line
        self.name = name          # lock id / sync name / callee / attr
        self.held = tuple(held)   # lock ids held at the event
        self.allow_lines = tuple(allow_lines)


class _Func:
    __slots__ = ("mod", "cls", "name", "node", "events", "entry_cls")

    def __init__(self, mod, cls, name, node):
        self.mod = mod
        self.cls = cls            # enclosing class name or None
        self.name = name          # "Class.meth" or "fn"
        self.node = node
        self.events: List[_Event] = []
        self.entry_cls: Optional[str] = None   # class that Thread()s us


class _Module:
    __slots__ = ("path", "key", "tree", "lines", "globals", "attr_locks",
                 "imports", "funcs", "thread_targets")

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path
        base = os.path.basename(path)
        self.key = base[:-3] if base.endswith(".py") else base
        self.tree = tree
        self.lines = source.splitlines()
        self.globals: Dict[str, str] = {}            # name -> lock id
        self.attr_locks: Dict[str, Dict[str, str]] = {}   # cls -> attr -> id
        self.imports: Dict[str, str] = {}            # alias -> module key
        self.funcs: Dict[str, _Func] = {}            # qualname -> _Func
        # (target dotted-name, enclosing class or None, line)
        self.thread_targets: List[Tuple[str, Optional[str], int]] = []


# --------------------------------------------------------------- phase 1a


def _scan_module(mod: _Module) -> None:
    """Lock tables, imports, function index, Thread(target=) registry."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and _is_factory(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    mod.globals[tgt.id] = "%s.%s" % (mod.key, tgt.id)

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                name = alias.asname or alias.name.rsplit(".", 1)[-1]
                mod.imports[name] = alias.name.rsplit(".", 1)[-1]

    def walk_funcs(body, cls: Optional[str]):
        for node in body:
            if isinstance(node, ast.ClassDef):
                walk_funcs(node.body, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = "%s.%s" % (cls, node.name) if cls else node.name
                mod.funcs[qual] = _Func(mod, cls, qual, node)
                _scan_locks_and_threads(mod, cls, node)

    walk_funcs(mod.tree.body, None)


def _scan_locks_and_threads(mod: _Module, cls: Optional[str],
                            fn: ast.AST) -> None:
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Attribute) and \
                isinstance(node.targets[0].value, ast.Name) and \
                node.targets[0].value.id == "self" and cls:
            attr = node.targets[0].attr
            table = mod.attr_locks.setdefault(cls, {})
            val = node.value
            if _is_factory(val):
                # Condition(self.other) shares the other lock's node
                aliased = None
                if _dotted(val.func).rsplit(".", 1)[-1] == "Condition" \
                        and val.args:
                    other = _dotted(val.args[0])
                    if other.startswith("self."):
                        aliased = table.get(other[5:])
                table[attr] = aliased or "%s.%s.%s" % (mod.key, cls, attr)
            elif isinstance(val, ast.ListComp) and _is_factory(val.elt):
                table[attr] = "%s.%s.%s[]" % (mod.key, cls, attr)
        elif isinstance(node, ast.Call) and \
                _dotted(node.func).rsplit(".", 1)[-1] in _THREAD_LEAVES:
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = _dotted(kw.value)
                    if tgt:
                        mod.thread_targets.append((tgt, cls, node.lineno))


# --------------------------------------------------------------- phase 1b


class _FnWalk(ast.NodeVisitor):
    """Per-function event walk with a held-lock stack (``with`` plus bare
    ``acquire()``/``release()``), resolving lock expressions through the
    module tables as it goes."""

    def __init__(self, prog: "_Program", func: _Func):
        self.prog = prog
        self.func = func
        self.held: List[Tuple[str, int]] = []    # (lock id, line)

    def run(self) -> None:
        for stmt in self.func.node.body:
            self.visit(stmt)

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        return self.prog.resolve_lock(self.func, expr)

    def _emit(self, kind, line, name):
        allow = [line] + [ln for _, ln in self.held]
        self.func.events.append(_Event(
            kind, line, name, held=[l for l, _ in self.held],
            allow_lines=allow))

    # deferred-callback discipline: a nested def/lambda body runs later,
    # outside the enclosing held-set — and its own lock use is opaque to
    # the tables, so it is skipped (the runtime witness covers it)
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node):
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            lock = self._resolve_lock(expr)
            if lock is not None:
                self._emit("acquire", expr.lineno, lock)
                self.held.append((lock, expr.lineno))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        if pushed:
            del self.held[-pushed:]

    visit_AsyncWith = visit_With

    def visit_Assign(self, node):
        self.visit(node.value)
        for tgt in node.targets:
            self._note_write(tgt)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        self._note_write(node.target)

    def _note_write(self, tgt: ast.AST) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._note_write(el)
            return
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value             # d[k] = v mutates d
        if not (isinstance(tgt, ast.Attribute) and
                isinstance(tgt.value, ast.Name)):
            return
        recv, attr = tgt.value.id, tgt.attr
        cls = self.func.cls or self.func.entry_cls
        if recv != "self" and self.func.entry_cls is None:
            return
        if cls and attr in self.prog.mod_of(self.func).attr_locks.get(
                cls, {}):
            return                       # the lock attr itself
        self._emit("write", tgt.lineno, "%s.%s" % (cls or "?", attr))

    def visit_Call(self, node):
        d = _dotted(node.func)
        leaf = d.rsplit(".", 1)[-1] if d else ""
        if leaf == "acquire" and isinstance(node.func, ast.Attribute):
            lock = self._resolve_lock(node.func.value)
            if lock is not None:
                self._emit("acquire", node.lineno, lock)
                self.held.append((lock, node.lineno))
        elif leaf == "release" and isinstance(node.func, ast.Attribute):
            lock = self._resolve_lock(node.func.value)
            if lock is not None:
                for i in range(len(self.held) - 1, -1, -1):
                    if self.held[i][0] == lock:
                        del self.held[i]
                        break
        elif leaf in _HOST_SYNC_METHODS or d in _SYNC_FULL:
            self._emit("sync", node.lineno, d)
        elif d and d not in ("super",):
            self._emit("call", node.lineno, d)
        self.generic_visit(node)


# ----------------------------------------------------------------- program


class _Program:
    def __init__(self, modules: List[_Module]):
        self.modules = modules
        self.by_key = {m.key: m for m in modules}
        # attr -> [(module, class, lock id)] across the program
        self.attr_index: Dict[str, List[Tuple[_Module, str, str]]] = {}
        for m in modules:
            for cls, table in m.attr_locks.items():
                for attr, lock in table.items():
                    self.attr_index.setdefault(attr, []).append(
                        (m, cls, lock))
        # method name -> [funcs] across the program
        self.meth_index: Dict[str, List[_Func]] = {}
        for m in modules:
            for qual, fn in m.funcs.items():
                self.meth_index.setdefault(
                    qual.rsplit(".", 1)[-1], []).append(fn)

    def mod_of(self, func: _Func) -> _Module:
        return self.by_key[func.mod.key]

    # ------------------------------------------------------- resolution
    def resolve_lock(self, func: _Func, expr: ast.AST) -> Optional[str]:
        mod = func.mod
        d = _lockish(expr)
        if not d:
            return None
        named = bool(_LOCK_NAME.search(d))
        parts = d.split(".")
        if len(parts) == 1:
            if d in mod.globals:
                return mod.globals[d]
            return "%s:%s:%s" % (mod.key, func.name, d) if named else None
        recv, attr = ".".join(parts[:-1]), parts[-1]
        if recv == "self" and func.cls:
            lock = mod.attr_locks.get(func.cls, {}).get(attr)
            if lock:
                return lock
        if recv in mod.imports:
            other = self.by_key.get(mod.imports[recv])
            if other and attr in other.globals:
                return other.globals[attr]
        if recv != "self":
            # thread-entry functions resolve foreign receivers against
            # the class that spawned them (the scheduler-loop idiom)
            if func.entry_cls:
                lock = mod.attr_locks.get(func.entry_cls, {}).get(attr)
                if lock:
                    return lock
            owners = self.attr_index.get(attr, ())
            if len(owners) == 1:
                return owners[0][2]
        if named:
            return "%s:%s:%s" % (mod.key, func.name, d)
        return None

    def resolve_callee(self, func: _Func, dotted: str) -> Optional[_Func]:
        mod = func.mod
        parts = dotted.split(".")
        if len(parts) == 1:
            return mod.funcs.get(dotted)
        recv, meth = ".".join(parts[:-1]), parts[-1]
        if recv == "self" and func.cls:
            hit = mod.funcs.get("%s.%s" % (func.cls, meth))
            if hit:
                return hit
        if recv in mod.imports:
            other = self.by_key.get(mod.imports[recv])
            if other:
                hit = other.funcs.get(meth)
                if hit:
                    return hit
        if func.entry_cls:
            hit = mod.funcs.get("%s.%s" % (func.entry_cls, meth))
            if hit:
                return hit
        owners = self.meth_index.get(meth, ())
        if len(owners) == 1:
            return owners[0]
        return None

    # ------------------------------------------------------ suppression
    def allowed(self, code: str,
                sites: Sequence[Tuple[_Module, int]]) -> bool:
        for mod, line in sites:
            if not (1 <= line <= len(mod.lines)):
                continue
            m = _ALLOW.search(mod.lines[line - 1])
            if m and code in [c.strip() for c in m.group(1).split(",")]:
                return True
        return False


def _loc(mod: _Module, line: int) -> str:
    return "%s:%d" % (mod.path, line)


# ------------------------------------------------------------------ driver


def analyze_sources(units, report: Optional[Report] = None) -> Report:
    """Run the whole-program pass over ``units`` — an iterable of
    ``(path, source)`` or ``(path, source, tree)`` covering every file
    that should resolve against each other (``lint_paths`` hands it the
    package; tests hand it fixtures)."""
    report = report if report is not None else Report(context="concurrency")
    modules: List[_Module] = []
    for unit in units:
        path, source = unit[0], unit[1]
        tree = unit[2] if len(unit) > 2 else None
        if tree is None:
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError:
                continue                 # lint_source already reported it
        modules.append(_Module(path, source, tree))
    if not modules:
        return report

    for mod in modules:
        _scan_module(mod)
    prog = _Program(modules)

    # thread-entry registry must exist before the event walk: entry
    # functions resolve foreign receivers through their spawning class
    entries: List[_Func] = []
    for mod in modules:
        for tgt, cls, _line in mod.thread_targets:
            fn = None
            if tgt.startswith("self.") and cls:
                fn = mod.funcs.get("%s.%s" % (cls, tgt[5:]))
            elif "." not in tgt:
                fn = mod.funcs.get(tgt)
            if fn is not None:
                if fn.entry_cls is None:
                    fn.entry_cls = None if fn.cls else cls
                entries.append(fn)

    for mod in modules:
        for fn in mod.funcs.values():
            _FnWalk(prog, fn).run()

    _check_interprocedural_sync(prog, report)
    _check_lock_order(prog, report)
    _check_unlocked_shared_state(prog, entries, report)
    return report


# ------------------------------------------------- interprocedural sync


def _check_interprocedural_sync(prog: _Program, report: Report) -> None:
    seen: Set[Tuple[str, int, str]] = set()
    for mod in prog.modules:
        for fn in mod.funcs.values():
            for ev in fn.events:
                if ev.kind != "call" or not ev.held:
                    continue
                g = prog.resolve_callee(fn, ev.name)
                if g is None or g is fn:
                    continue
                gmod = prog.mod_of(g)
                for sev in g.events:
                    if sev.kind != "sync":
                        continue
                    key = (mod.path, ev.line, sev.name)
                    if key in seen:
                        continue
                    seen.add(key)
                    sites = [(mod, ln) for ln in ev.allow_lines] + \
                            [(gmod, ln) for ln in sev.allow_lines]
                    if prog.allowed("lock-host-sync", sites):
                        continue
                    report.add(
                        "lock-host-sync", Severity.ERROR,
                        "call %s() while holding lock(s) [%s] reaches "
                        "host sync %s() at %s — the helper blocks on "
                        "the device under the caller's lock (the PR 2 "
                        "train_rcnn shape, one call deep)"
                        % (ev.name, ", ".join(ev.held), sev.name,
                           _loc(gmod, sev.line)),
                        path=mod.path, line=ev.line, func=fn.name)


# --------------------------------------------------------- lock ordering


class _Edge:
    __slots__ = ("chain", "sites")

    def __init__(self, chain: str, sites):
        self.chain = chain               # human acquisition chain
        self.sites = sites               # [(module, line)] for allow()


def _collect_edges(prog: _Program) -> Dict[Tuple[str, str], _Edge]:
    edges: Dict[Tuple[str, str], _Edge] = {}

    def add(a: str, b: str, chain: str, sites) -> None:
        if a == b:
            return
        edges.setdefault((a, b), _Edge(chain, sites))

    for mod in prog.modules:
        for fn in mod.funcs.values():
            for ev in fn.events:
                if ev.kind == "acquire" and ev.held:
                    for a in ev.held:
                        add(a, ev.name,
                            "%s (%s) acquires %s while holding %s"
                            % (fn.name, _loc(mod, ev.line), ev.name, a),
                            [(mod, ln) for ln in ev.allow_lines])
                elif ev.kind == "call" and ev.held:
                    g = prog.resolve_callee(fn, ev.name)
                    if g is None or g is fn:
                        continue
                    gmod = prog.mod_of(g)
                    for gev in g.events:
                        if gev.kind != "acquire":
                            continue
                        for a in ev.held:
                            add(a, gev.name,
                                "%s (%s) calls %s() which acquires %s "
                                "at %s while the caller holds %s"
                                % (fn.name, _loc(mod, ev.line), ev.name,
                                   gev.name, _loc(gmod, gev.line), a),
                                [(mod, ln) for ln in ev.allow_lines] +
                                [(gmod, ln) for ln in gev.allow_lines])
    return edges


def _find_cycles(edges: Dict[Tuple[str, str], _Edge],
                 limit: int = 64) -> List[List[str]]:
    """Elementary cycles, shortest-first per start node, deduped by node
    set; bounded so a pathological graph can't hang the lint."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
    cycles: List[List[str]] = []
    seen_sets: Set[frozenset] = set()
    for start in sorted(graph):
        # BFS over simple paths from start back to start
        queue: List[List[str]] = [[start]]
        steps = 0
        while queue and steps < 10000 and len(cycles) < limit:
            steps += 1
            path = queue.pop(0)
            for nxt in graph.get(path[-1], ()):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        cycles.append(path[:])
                elif nxt not in path and len(path) < 6:
                    queue.append(path + [nxt])
    return cycles


def _check_lock_order(prog: _Program, report: Report) -> None:
    edges = _collect_edges(prog)
    for cycle in _find_cycles(edges):
        ring = cycle + [cycle[0]]
        used = [edges[(ring[i], ring[i + 1])] for i in range(len(cycle))]
        sites = [s for e in used for s in e.sites]
        if prog.allowed("lock-order-cycle", sites):
            continue
        chains = "; ".join(e.chain for e in used)
        anchor_mod, anchor_line = used[0].sites[0]
        report.add(
            "lock-order-cycle", Severity.ERROR,
            "lock-order cycle %s: %s — two threads driving different "
            "edges of this cycle concurrently deadlock; pick one global "
            "order (or collapse the locks)"
            % (" -> ".join(ring), chains),
            path=anchor_mod.path, line=anchor_line,
            func=used[0].chain.split(" ", 1)[0])


# ------------------------------------------------- unlocked shared state


def _check_unlocked_shared_state(prog: _Program, entries: List[_Func],
                                 report: Report) -> None:
    # entry-reachable = the Thread targets plus their one-level callees
    reach: Set[int] = set()
    for fn in entries:
        reach.add(id(fn))
        for ev in fn.events:
            if ev.kind == "call":
                g = prog.resolve_callee(fn, ev.name)
                if g is not None:
                    reach.add(id(g))

    # (module, class.attr) -> locked write / unlocked-in-entry write
    locked: Dict[Tuple[str, str], Tuple[_Module, _Func, int]] = {}
    unlocked: Dict[Tuple[str, str], Tuple[_Module, _Func, int]] = {}
    for mod in prog.modules:
        for fn in mod.funcs.values():
            leaf = fn.name.rsplit(".", 1)[-1]
            for ev in fn.events:
                if ev.kind != "write" or ev.name.startswith("?."):
                    continue
                key = (mod.key, ev.name)
                if ev.held:
                    locked.setdefault(key, (mod, fn, ev.line))
                elif id(fn) in reach and leaf != "__init__":
                    unlocked.setdefault(key, (mod, fn, ev.line))

    for key in sorted(set(locked) & set(unlocked)):
        lmod, lfn, lline = locked[key]
        umod, ufn, uline = unlocked[key]
        if prog.allowed("unlocked-shared-state", [(umod, uline)]):
            continue
        report.add(
            "unlocked-shared-state", Severity.WARNING,
            "attribute %s is written under a lock in %s (%s) but "
            "written with NO lock held on the thread-entry path %s — "
            "the lock discipline protecting it has a hole (torn "
            "read/write across threads)"
            % (key[1], lfn.name, _loc(lmod, lline), ufn.name),
            path=umod.path, line=uline, func=ufn.name)

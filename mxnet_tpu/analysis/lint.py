"""AST-based concurrency/perf lint for the codebase itself.

The serving and executor layers mix Python locks with device dispatch, and
the exact shapes that caused PR 2's deadlock and latency bugs are visible
in the AST without running anything:

* ``lock-host-sync`` (ERROR) — a host sync (``.asnumpy()``,
  ``block_until_ready``, ``jax.device_get``, ``future.result()``) while a
  lock/condition is held: every other thread needing that lock now waits
  on the device, and if the synced computation needs the lock-holder
  (callback re-entry) the process deadlocks — the PR 2 train_rcnn shape.
* ``lock-dispatch`` (WARNING) — jax dispatch (``jax.*``/``jnp.*`` calls,
  ``nd.array``) under a lock: serializes the accelerator behind a Python
  mutex and widens every race window.
* ``wall-clock`` (WARNING) — ``time.time()`` in latency/throughput math:
  wall clocks jump with NTP; deadlines and p99s must use
  ``time.monotonic()``/``perf_counter()``.
* ``eager-loop-sync`` (WARNING) — a host sync (``asnumpy``/``asscalar``/
  ``wait_to_read``/``block_until_ready``) lexically inside the batch loop
  of a training/eval-loop function (``fit``, ``score``, or ``*_loop``):
  one sync per batch serializes the whole pipeline behind host
  round-trips — the exact regime the async fit loop eliminated
  (docs/architecture/async_loop.md). The DEFERRED-sync pattern is not
  flagged: syncs inside ``get``/``get_name_value``/``_sync*`` bodies (the
  metric log-boundary fetch) and the ``InflightWindow`` flow-control
  waits live outside loop-function bodies by construction.

* ``signal-unsafe`` (ERROR/WARNING) — lock acquisition or
  allocation-heavy calls lexically inside a **registered signal
  handler** (a function installed via ``signal.signal``): a signal can
  land while the interrupted frame already holds the very lock the
  handler wants (logging's module lock, a profiler counter lock, the
  GIL-guarded allocator arenas), deadlocking the process — the hazard
  class the PR 5 SIGTERM handler dodges by hand by setting ONE flag and
  returning (checkpoint/manager.py ``install_sigterm``).

Intentional sites are suppressed inline with ``# mx-lint: allow(<code>)``
(on the offending line or the enclosing ``with`` line); historical debt is
carried by a checked-in baseline (:func:`load_baseline`/:func:`diff_baseline`)
so CI fails only on NEW findings — and :func:`stale_baseline` reports
suppressions the code no longer needs, which the CI gate treats as
findings too (a baseline that only grows is a baseline nobody trusts).
"""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from .findings import Finding, Report, Severity

__all__ = ["lint_paths", "lint_source", "load_baseline", "write_baseline",
           "diff_baseline", "stale_baseline", "baseline_key"]

_LOCK_NAME = re.compile(r"(lock|cond|mutex|sem)", re.IGNORECASE)
_ALLOW = re.compile(r"#\s*mx-lint:\s*allow\(([\w\s,-]+)\)")

# attribute-call names that synchronize with the device / block the thread
_HOST_SYNC_METHODS = {"asnumpy", "wait_to_read", "block_until_ready",
                      "device_get", "item", "result"}
# the subset that is unambiguous in a batch loop (`.result()`/`.item()`
# are too generic to flag outside a lock context)
_LOOP_SYNC_METHODS = {"asnumpy", "asscalar", "wait_to_read",
                      "block_until_ready", "device_get"}
# training/eval-loop owners: one sync per iteration here gates steps/s
_LOOP_FUNC = re.compile(r"^(fit|score)$|_loop$")
# module roots whose calls dispatch device work
_DISPATCH_ROOTS = {"jax", "jnp"}
_DISPATCH_ARRAY_FNS = {"array", "asarray", "device_put"}


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted-name rendering of a call target / with-context."""
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return "%s.%s" % (base, node.attr) if base else node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _dotted(node.func)
    return ""


def _is_lock_expr(expr: ast.AST) -> bool:
    return bool(_LOCK_NAME.search(_dotted(expr)))


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, source: str, report: Report):
        self.path = path
        self.lines = source.splitlines()
        self.report = report
        self.lock_stack: List[Tuple[str, int]] = []   # (lock name, line)
        self.func_stack: List[str] = []
        self.loop_depth = 0

    # ------------------------------------------------------- suppression
    def _allowed(self, code: str, *lines: int) -> bool:
        for ln in lines:
            if ln is None or not (1 <= ln <= len(self.lines)):
                continue
            m = _ALLOW.search(self.lines[ln - 1])
            if m and code in [c.strip() for c in m.group(1).split(",")]:
                return True
        return False

    def _add(self, code: str, severity: Severity, message: str,
             line: int) -> None:
        lock_lines = [ln for _, ln in self.lock_stack]
        if self._allowed(code, line, *lock_lines):
            return
        self.report.add(code, severity, message, path=self.path, line=line,
                        func=".".join(self.func_stack) or "<module>")

    # -------------------------------------------------------- traversal
    def visit_ClassDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        # a new function body does NOT inherit the enclosing with-lock
        # textually... but nested defs under `with lock:` are usually
        # callbacks invoked elsewhere — reset the lock context for them
        saved, self.lock_stack = self.lock_stack, []
        saved_loops, self.loop_depth = self.loop_depth, 0
        self.generic_visit(node)
        self.lock_stack = saved
        self.loop_depth = saved_loops
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        # a lambda built under a lock runs later, outside it — same
        # deferred-callback reset as nested defs
        saved, self.lock_stack = self.lock_stack, []
        self.generic_visit(node)
        self.lock_stack = saved

    def visit_With(self, node):
        held = [(_dotted(item.context_expr), item.context_expr.lineno)
                for item in node.items if _is_lock_expr(item.context_expr)]
        self.lock_stack.extend(held)
        self.generic_visit(node)
        if held:
            del self.lock_stack[-len(held):]

    visit_AsyncWith = visit_With

    def visit_For(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_AsyncFor = visit_For
    visit_While = visit_For

    def _in_loop_func(self) -> bool:
        return bool(self.loop_depth) and bool(self.func_stack) and \
            bool(_LOOP_FUNC.search(self.func_stack[-1]))

    def visit_Call(self, node):
        name = _dotted(node.func)
        leaf = name.rsplit(".", 1)[-1]
        root = name.split(".", 1)[0]
        line = node.lineno

        if name == "time.time":
            self._add(
                "wall-clock", Severity.WARNING,
                "time.time() is wall-clock (jumps with NTP) — use "
                "time.monotonic()/perf_counter() for latency/deadline "
                "math", line)

        if self._in_loop_func() and (
                leaf in _LOOP_SYNC_METHODS or name in (
                    "jax.block_until_ready", "jax.device_get")):
            self._add(
                "eager-loop-sync", Severity.WARNING,
                "host sync %r inside the batch loop of %r — one device "
                "round-trip per batch gates steps/s; accumulate on "
                "device and defer the fetch to a log boundary "
                "(EvalMetric.update_device / InflightWindow, "
                "docs/architecture/async_loop.md)"
                % (name + "()", self.func_stack[-1]), line)

        if self.lock_stack:
            locks = ", ".join(l for l, _ in self.lock_stack)
            if leaf in _HOST_SYNC_METHODS or name in (
                    "jax.block_until_ready", "jax.device_get"):
                self._add(
                    "lock-host-sync", Severity.ERROR,
                    "host sync %r while holding lock(s) [%s] — other "
                    "threads queue behind the device, and callback "
                    "re-entry deadlocks (the PR 2 train_rcnn shape)"
                    % (name + "()", locks), line)
            elif root in _DISPATCH_ROOTS or (
                    leaf in _DISPATCH_ARRAY_FNS and
                    root in ("nd", "nd_mod", "ndarray", "jax", "jnp")):
                self._add(
                    "lock-dispatch", Severity.WARNING,
                    "jax dispatch %r under lock(s) [%s] — the accelerator "
                    "is serialized behind a Python mutex" % (name, locks),
                    line)

        # bare acquire()/release() participates in lock_stack exactly
        # like `with` — the try/finally idiom must not be invisible to
        # the under-lock checks above (function exit still resets the
        # stack, bounding an unmatched acquire to its function)
        if isinstance(node.func, ast.Attribute):
            recv = _dotted(node.func.value)
            if leaf == "acquire" and _LOCK_NAME.search(recv):
                self.lock_stack.append((recv, line))
            elif leaf == "release" and _LOCK_NAME.search(recv):
                for i in range(len(self.lock_stack) - 1, -1, -1):
                    if self.lock_stack[i][0] == recv:
                        del self.lock_stack[i]
                        break
        self.generic_visit(node)


# methods that take a lock / block — fatal if the interrupted frame
# already holds the other side (python's own signal docs: handlers must
# be reentrant). The first set is unambiguous; the second is flagged
# only when the receiver's name looks synchronization-flavored
# (str.join / dict.get would otherwise drown the rule in noise).
_SIGNAL_LOCKING_METHODS = {"acquire", "notify", "notify_all"}
_SIGNAL_BLOCKING_METHODS = {"wait", "join", "put", "get", "set"}
_SIGNAL_SYNC_RECEIVER = re.compile(
    r"(lock|cond|mutex|sem|queue|thread|event)", re.IGNORECASE)
# call roots that allocate heavily or take module-level locks (logging's
# handler lock is the classic signal deadlock)
_SIGNAL_HEAVY_ROOTS = {"logging", "jax", "jnp", "np", "numpy", "nd",
                       "print", "open"}


class _SignalScanner:
    """Second pass: find functions registered via ``signal.signal`` and
    flag lock-taking / allocation-heavy calls lexically inside them.
    Registration-by-name is resolved within the file (plain names AND
    ``self._handler``-style attributes, both common in this codebase)."""

    def __init__(self, path: str, source: str, report: Report):
        self.path = path
        self.lines = source.splitlines()
        self.report = report

    def _allowed(self, line: int) -> bool:
        if not (1 <= line <= len(self.lines)):
            return False
        m = _ALLOW.search(self.lines[line - 1])
        return bool(m and "signal-unsafe" in
                    [c.strip() for c in m.group(1).split(",")])

    def scan(self, tree: ast.AST) -> None:
        defs: Dict[str, ast.AST] = {}
        handlers: List[Tuple[ast.AST, str]] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
            elif isinstance(node, ast.Call) and \
                    _dotted(node.func) in ("signal.signal",
                                           "signal.sigaction") and \
                    len(node.args) >= 2:
                target = node.args[1]
                if isinstance(target, ast.Lambda):
                    handlers.append((target, "<lambda>"))
                elif isinstance(target, ast.Name):
                    handlers.append((target.id, target.id))
                elif isinstance(target, ast.Attribute):
                    handlers.append((target.attr, target.attr))
        seen = set()
        for target, name in handlers:
            node = defs.get(target) if isinstance(target, str) else target
            if node is None or id(node) in seen:
                continue
            seen.add(id(node))
            self._scan_handler(node, name)

    def _scan_handler(self, fn: ast.AST, name: str) -> None:
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_lock_expr(item.context_expr):
                        self._add(
                            Severity.ERROR, name,
                            "acquires lock %r" % _dotted(item.context_expr),
                            item.context_expr.lineno)
            elif isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                leaf = dotted.rsplit(".", 1)[-1]
                root = dotted.split(".", 1)[0]
                receiver = dotted.rsplit(".", 1)[0] if "." in dotted else ""
                if leaf in _SIGNAL_LOCKING_METHODS and "." in dotted:
                    self._add(
                        Severity.ERROR, name,
                        "calls %s() — takes a lock/blocks" % dotted,
                        node.lineno)
                elif leaf in _SIGNAL_BLOCKING_METHODS and \
                        _SIGNAL_SYNC_RECEIVER.search(receiver):
                    self._add(
                        Severity.ERROR, name,
                        "calls %s() — takes a lock/blocks" % dotted,
                        node.lineno)
                elif root in _SIGNAL_HEAVY_ROOTS:
                    self._add(
                        Severity.WARNING, name,
                        "calls %s() — allocation-heavy / takes module "
                        "locks" % dotted, node.lineno)

    def _add(self, severity: Severity, handler: str, what: str,
             line: int) -> None:
        if self._allowed(line):
            return
        self.report.add(
            "signal-unsafe", severity,
            "registered signal handler %r %s: a signal can land while "
            "the interrupted frame holds the other side and deadlock "
            "the process — handlers must only set a flag (the PR 5 "
            "install_sigterm discipline)" % (handler, what),
            path=self.path, line=line, func=handler)


def lint_source(source: str, path: str = "<string>",
                report: Optional[Report] = None,
                concurrency: bool = True) -> Report:
    """Lint one source blob. ``concurrency=True`` (the default) also runs
    the whole-program lock-order pass over this single file — right for
    standalone snippets and fixtures; ``lint_paths`` passes ``False`` and
    runs that pass ONCE over all files so cross-module cycles resolve."""
    report = report if report is not None else Report(context="lint")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        report.add("parse-error", Severity.ERROR,
                   "cannot parse: %s" % exc, path=path,
                   line=exc.lineno or 0)
        return report
    _FileLinter(path, source, report).visit(tree)
    _SignalScanner(path, source, report).scan(tree)
    if concurrency:
        from .concurrency import analyze_sources
        analyze_sources([(path, source, tree)], report)
    return report


def lint_paths(paths, report: Optional[Report] = None,
               exclude=("native/vendor",)) -> Report:
    """Lint every ``.py`` file under ``paths`` (files or directories),
    then run the whole-program concurrency pass over the full file set
    (lock names and helper calls resolve across modules)."""
    report = report if report is not None else Report(context="lint")
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for f in sorted(filenames):
                full = os.path.join(dirpath, f)
                if f.endswith(".py") and not any(e in full
                                                 for e in exclude):
                    files.append(full)
    units = []
    for f in sorted(files):
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        lint_source(src, path=f, report=report, concurrency=False)
        units.append((f, src))
    from .concurrency import analyze_sources
    analyze_sources(units, report)
    return report


# ------------------------------------------------------------------ baseline
# Keys are (relpath, code, enclosing function) with a count — stable under
# line-number drift, so refactors that merely move debt don't churn the
# file, while any NEW site in a function bumps its count and fails CI.


def baseline_key(f: Finding, root: str) -> str:
    rel = os.path.relpath(f.path, root) if f.path else "<none>"
    return "%s::%s::%s" % (rel.replace(os.sep, "/"), f.code,
                           f.func or "<module>")


def _key_counts(report: Report, root: str) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in report:
        if f.code == "cost-model":
            continue
        k = baseline_key(f, root)
        counts[k] = counts.get(k, 0) + 1
    return counts


def write_baseline(report: Report, path: str, root: str) -> int:
    """Write the aggregated baseline; returns the number of KEYS written
    (several same-key findings collapse into one counted key)."""
    payload = {
        "__doc__": "mx-lint baseline: known findings keyed by "
                   "path::code::function with counts; CI fails on drift "
                   "in EITHER direction — a count exceeding its baseline "
                   "(new finding) or a baseline exceeding the count "
                   "(stale suppression). Regenerate with "
                   "`python -m mxnet_tpu.analysis lint --update-baseline`.",
        "findings": _key_counts(report, root),
    }
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(payload["findings"])


def load_baseline(path: str) -> Dict[str, int]:
    with open(path) as fh:
        payload = json.load(fh)
    return {k: int(v) for k, v in payload.get("findings", {}).items()}


def stale_baseline(report: Report, baseline: Dict[str, int],
                   root: str) -> Dict[str, int]:
    """Baseline keys whose counted debt the code no longer carries
    (key -> excess). Stale suppressions are findings too: they mask the
    next REAL finding introduced at that key, so the CI gate fails on
    drift in *either* direction and the fix is
    ``python -m mxnet_tpu.analysis lint --update-baseline``."""
    counts = _key_counts(report, root)
    return {k: v - counts.get(k, 0) for k, v in sorted(baseline.items())
            if v > counts.get(k, 0)}


def diff_baseline(report: Report, baseline: Dict[str, int],
                  root: str) -> List[Finding]:
    """Findings NOT covered by the baseline (per-key overflow keeps the
    textually-last findings of that key, which skews new-at-the-bottom —
    good enough for a gate whose fix is 'look at this function')."""
    budget = dict(baseline)
    fresh: List[Finding] = []
    for f in report:
        if f.code == "cost-model":
            continue
        k = baseline_key(f, root)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            fresh.append(f)
    return fresh

"""Roofline/utilization pass: why is MFU what it is?

PR 6 made ``obs_mfu`` always-on; this pass attaches the *why*. Two FLOP
accountings are reconciled per executable:

* the **analysis cost model** (static, per-node — what ``obs_mfu``
  multiplies by steps/s), and
* XLA's own **compiled cost** (``compiled.cost_analysis()`` — FLOPs and
  bytes the scheduler actually planned, post-fusion/partitioning).

When the two disagree beyond tolerance the model is lying
(``flop-model-drift`` — the exact undercount shape the PR 6
flash-attention fix repaired), and every MFU number derived from it
inherits the lie. Each program is then classified against the device
roofline: arithmetic intensity (FLOPs / bytes accessed) vs the device
balance point (peak FLOP/s / HBM bandwidth). A memory-bound program's
attainable MFU is ``intensity / balance`` — if measured ``obs_mfu`` is
already there, the gap is the roofline, not scheduling, and the fix is
more intensity (bigger batch, fusion, remat); if measured MFU is far
below attainable, the gap IS scheduling (input stalls, host syncs,
compile churn) and the async-loop counters are the next place to look.

Peak FLOP/s comes from the one table in :mod:`mxnet_tpu.obs.mfu`; HBM
bandwidth from the table here (override: ``MXNET_TPU_ANALYZE_HBM_GBPS``
— required on CPU test rigs where the device kind is unknown).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .findings import Report, Severity

__all__ = ["classify", "analyze_executable", "explain", "hbm_gbps",
           "HBM_GBPS_BY_DEVICE_KIND", "FLOP_MODEL_DRIFT_TOL"]

# HBM bandwidth (GB/s) by TPU generation, device_kind substring match —
# the denominator of the balance point. Sibling of
# obs.mfu.PEAK_FLOPS_BY_DEVICE_KIND (peak FLOP/s stays single-sourced
# there).
HBM_GBPS_BY_DEVICE_KIND = [
    ("v5 lite", 819.0), ("v5e", 819.0), ("v5p", 2765.0),
    ("v6", 1640.0), ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0)]

# |compiled/model - 1| beyond this is a drift finding
FLOP_MODEL_DRIFT_TOL = 0.25


def hbm_gbps(device_kind: Optional[str] = None) -> Optional[float]:
    """HBM GB/s: the ``MXNET_TPU_ANALYZE_HBM_GBPS`` override wins, else
    the device-kind table; None when unknown (classification is then
    skipped, never fabricated)."""
    from .sharding_passes import device_table_lookup
    return device_table_lookup(HBM_GBPS_BY_DEVICE_KIND,
                               "MXNET_TPU_ANALYZE_HBM_GBPS",
                               default=None, device_kind=device_kind)


def classify(flops: float, bytes_accessed: float,
             device_kind: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Roofline classification of one program: arithmetic intensity vs
    the device balance point. None when peak/bandwidth are unknown."""
    from ..obs import mfu as _mfu
    peak = _mfu.peak_flops(device_kind)
    bw = hbm_gbps(device_kind)
    if not peak or not bw or not flops or not bytes_accessed:
        return None
    intensity = flops / bytes_accessed
    balance = peak / (bw * 1e9)
    attainable = min(1.0, intensity / balance)
    return {
        "intensity_flops_per_byte": round(intensity, 3),
        "balance_flops_per_byte": round(balance, 3),
        "bound": "compute" if intensity >= balance else "memory",
        "attainable_mfu": round(attainable, 4),
        "peak_flops": peak,
        "hbm_gbps": bw,
    }


def analyze_executable(fn, *args, model_flops: Optional[float] = None,
                       in_shardings=None, static_argnums=(),
                       context: str = "roofline",
                       report: Optional[Report] = None,
                       **kwargs) -> Report:
    """Compile ``fn(*args)`` and reconcile XLA's cost with the model.

    ``Report.extras["roofline"]``: compiled FLOPs / bytes accessed /
    XLA's own temp (activation) bytes from ``memory_analysis()``, the
    classification, and — when ``model_flops`` is given (the analysis
    cost model's count for the same program) — the model/compiled ratio,
    with a ``flop-model-drift`` WARNING beyond ±25%. Compiled counts are
    **per device** after partitioning; the caller's ``model_flops`` must
    be per-device too (divide the whole-program count by the mesh size).
    """
    import jax

    report = report if report is not None else Report(context=context)
    jit_kw: Dict[str, Any] = {"static_argnums": static_argnums}
    if in_shardings is not None:
        jit_kw["in_shardings"] = in_shardings
    compiled = jax.jit(fn, **jit_kw).lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops") or 0)
    nbytes = float(ca.get("bytes accessed") or 0)
    roof: Dict[str, Any] = {
        "compiled_flops": flops,
        "compiled_bytes_accessed": nbytes,
    }
    try:
        mem = compiled.memory_analysis()
        roof["xla_temp_bytes"] = int(getattr(mem, "temp_size_in_bytes", 0))
        roof["xla_argument_bytes"] = int(
            getattr(mem, "argument_size_in_bytes", 0))
        roof["xla_output_bytes"] = int(
            getattr(mem, "output_size_in_bytes", 0))
    except Exception:                                       # noqa: BLE001
        pass
    cls = classify(flops, nbytes)
    if cls:
        roof.update(cls)
        report.add(
            "roofline", Severity.INFO,
            "%s-bound: intensity %.3g FLOP/byte vs balance %.3g — "
            "attainable MFU %.2f (%.3g GFLOP, %.3g MB accessed)"
            % (cls["bound"], cls["intensity_flops_per_byte"],
               cls["balance_flops_per_byte"], cls["attainable_mfu"],
               flops / 1e9, nbytes / 1e6),
            detail=dict(roof))
    if model_flops:
        ratio = flops / model_flops if model_flops else float("inf")
        roof["model_flops"] = float(model_flops)
        roof["model_ratio"] = round(ratio, 4)
        if abs(ratio - 1.0) > FLOP_MODEL_DRIFT_TOL and flops:
            report.add(
                "flop-model-drift", Severity.WARNING,
                "analysis FLOP model says %.4g but XLA compiled-cost says "
                "%.4g (ratio %.2f) — the model is mis-counting this "
                "program's ops (the PR 6 flash-attention undercount "
                "shape) and obs_mfu inherits the error; fix the "
                "_node_flops rule for the dominant op"
                % (model_flops, flops, ratio),
                detail={"model_flops": float(model_flops),
                        "compiled_flops": flops, "ratio": ratio})
    report.extras["roofline"] = roof
    return report


def explain(flops: float, bytes_moved: float,
            measured_mfu: Optional[float] = None) -> Optional[Dict[str, Any]]:
    """The ``mx.obs.report()`` reconciliation: classify a program from
    its static cost-model counts and, given the measured ``obs_mfu``,
    say which side of the gap to attack. Returns None when the device
    roofline is unknown."""
    cls = classify(flops, bytes_moved)
    if cls is None:
        return None
    if measured_mfu is not None:
        attainable = cls["attainable_mfu"]
        cls["measured_mfu"] = round(measured_mfu, 4)
        if attainable > 0:
            cls["roofline_fraction"] = round(measured_mfu / attainable, 3)
        if cls["bound"] == "memory" and measured_mfu >= 0.8 * attainable:
            cls["why"] = ("memory-bound at the roofline: measured MFU "
                          "%.2f of attainable %.2f — raise intensity "
                          "(bigger batch / remat / fusion), not "
                          "scheduling" % (measured_mfu, attainable))
        elif measured_mfu < 0.5 * attainable:
            cls["why"] = ("well below the %s roofline (measured %.2f vs "
                          "attainable %.2f) — the gap is scheduling: "
                          "check loop_* counters for input stalls, host "
                          "syncs, recompiles"
                          % (cls["bound"], measured_mfu, attainable))
        else:
            cls["why"] = ("approaching the %s roofline (measured %.2f "
                          "vs attainable %.2f)"
                          % (cls["bound"], measured_mfu, attainable))
    return cls

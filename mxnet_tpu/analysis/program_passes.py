"""Post-trace hazard checks over jaxprs — the program-layer audits.

Where the graph passes verify the declarative Symbol, these verify what a
trace actually *captured* — the hazards that produced PR 1's and PR 2's
production bugs are all visible in the jaxpr:

* ``baked-const`` — closure-captured constants baked into the program.
  Big ones bloat every executable and re-upload per compile; ANY captured
  constant is a cache-identity hazard (the PR 1 ``Scale(2.0)``/
  ``Scale(3.0)`` OpDef collision: two closures over different constants
  aliased onto one compiled program).
* ``f64-promotion`` — a program whose *inputs* are sub-f64 floats but
  which computes in float64 somewhere (a numpy scalar or python float
  promoted under x64): 2x memory + emulated arithmetic on TPU.
* ``host-callback`` — ``pure_callback``/``io_callback`` primitives force
  the synchronous dispatch path (the PR 2 train_rcnn deadlock shape).
* ``donation`` — donated inputs that are returned unchanged (the caller's
  buffer is invalidated while an output aliases it) or never consumed.

``analyze_program(fn, *args)`` traces with ``jax.make_jaxpr`` (jitted
functions trace through) and walks every sub-jaxpr (pjit/scan/cond/
custom_vjp bodies) recursively.
"""
from __future__ import annotations

from typing import Any, Iterable, List, Optional, Tuple

import numpy as np

from .findings import Report, Severity

__all__ = ["analyze_program", "analyze_jaxpr"]

# captured consts >= the warn bound get flagged; >= the error bound they
# are compile-time/HBM hazards in their own right
CONST_BYTES_WARN = 1 << 16       # 64 KiB
CONST_BYTES_ERROR = 1 << 26      # 64 MiB

_CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "python_callback",
                        "outside_call", "host_callback_call")


def _iter_jaxprs(jaxpr) -> Iterable[Tuple[Any, List[Any]]]:
    """Yield (jaxpr, consts) for a jaxpr and every sub-jaxpr reachable
    through eqn params (pjit, scan, while, cond branches, custom_vjp)."""
    from jax._src import core as _core

    seen = set()

    def walk(j, consts):
        if id(j) in seen:
            return
        seen.add(id(j))
        yield j, consts
        for eqn in j.eqns:
            for v in eqn.params.values():
                for sub in _as_jaxprs(v):
                    yield from walk(*sub)

    def _as_jaxprs(v):
        if isinstance(v, _core.ClosedJaxpr):
            yield (v.jaxpr, list(v.consts))
        elif isinstance(v, _core.Jaxpr):
            yield (v, [])
        elif isinstance(v, (list, tuple)):
            for x in v:
                yield from _as_jaxprs(x)

    yield from walk(jaxpr, [])


def _unwrap_pjit(closed):
    """Peel the trivial outer pjit wrapper ``make_jaxpr(jit(f))`` builds, so
    invar positions line up with the user's flattened arguments and consts
    are visible at the top level."""
    j = closed
    while len(j.jaxpr.eqns) == 1 and \
            j.jaxpr.eqns[0].primitive.name in ("pjit", "jit"):
        eqn = j.jaxpr.eqns[0]
        inner = eqn.params.get("jaxpr")
        if inner is None or list(eqn.invars) != list(j.jaxpr.invars) or \
                len(eqn.outvars) != len(j.jaxpr.outvars):
            break
        j = inner
    return j


def _const_bytes(c) -> int:
    nbytes = getattr(c, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    try:
        return int(np.asarray(c).nbytes)
    except Exception:                                       # noqa: BLE001
        return 0


def _aval_of(v):
    return getattr(v, "aval", None)


def _is_float(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.floating)


# ------------------------------------------------------------------ passes


def _check_baked_consts(report, jaxprs, const_bytes_warn,
                        const_bytes_error):
    for j, consts in jaxprs:
        for cv, c in zip(j.constvars, consts):
            n = _const_bytes(c)
            if n < const_bytes_warn:
                continue
            sev = Severity.ERROR if n >= const_bytes_error \
                else Severity.WARNING
            aval = _aval_of(cv)
            report.add(
                "baked-const", sev,
                "closure-captured constant %s%s (%d bytes) is baked into "
                "the program — pass it as an argument: baked constants "
                "bloat every executable, re-upload per compile, and make "
                "the closure part of the program's identity (the PR 1 "
                "OpDef signature-collision shape)"
                % (getattr(aval, "dtype", type(c).__name__),
                   list(getattr(aval, "shape", ())), n),
                detail={"nbytes": n,
                        "shape": list(getattr(aval, "shape", ()))})


def _check_f64(report, main, jaxprs):
    in_dtypes = [getattr(_aval_of(v), "dtype", None)
                 for v in main.invars]
    float_ins = [d for d in in_dtypes if d is not None and _is_float(d)]
    if not float_ins or all(np.dtype(d) == np.float64 for d in float_ins):
        return   # no float inputs, or intentionally f64 end-to-end
    for j, consts in jaxprs:
        for cv, c in zip(j.constvars, consts):
            if getattr(c, "dtype", None) is not None and \
                    np.dtype(c.dtype) == np.float64:
                report.add(
                    "f64-promotion", Severity.WARNING,
                    "float64 constant %s captured in a program with %s "
                    "inputs — arithmetic promotes to f64 (2x memory, "
                    "emulated on TPU); cast the constant or use a python "
                    "float" % (list(getattr(c, "shape", ())),
                               sorted({str(d) for d in float_ins})))
                return
        for eqn in j.eqns:
            for ov in eqn.outvars:
                aval = _aval_of(ov)
                dt = getattr(aval, "dtype", None)
                if dt is None or np.dtype(dt) != np.float64 or \
                        not _is_float(dt):
                    continue
                srcs = sorted({
                    str(getattr(_aval_of(iv), "dtype", "?"))
                    for iv in eqn.invars if _aval_of(iv) is not None})
                if "float64" in srcs:
                    continue   # promotion happened upstream; report once
                report.add(
                    "f64-promotion", Severity.WARNING,
                    "primitive %r promotes %s to float64 — a numpy "
                    "scalar/f64 literal leaked into an f32 program under "
                    "x64 (2x memory, emulated arithmetic on TPU)"
                    % (eqn.primitive.name, srcs or ["(consts)"]),
                    detail={"primitive": eqn.primitive.name,
                            "input_dtypes": srcs})
                return


def _check_host_callbacks(report, jaxprs):
    found = {}
    for j, _ in jaxprs:
        for eqn in j.eqns:
            name = eqn.primitive.name
            if any(name.startswith(p) for p in _CALLBACK_PRIMITIVES):
                found[name] = found.get(name, 0) + 1
    for name, count in found.items():
        report.add(
            "host-callback", Severity.WARNING,
            "%d %r primitive(s) in the program — host callbacks force "
            "synchronous dispatch with the frontend (the PR 2 sync path: "
            "executor._sync_host_callbacks) and stall the accelerator "
            "pipeline every step" % (count, name),
            detail={"primitive": name, "count": count})


def _check_donation(report, main, donate_argnums, n_args):
    if not donate_argnums:
        return
    donate = set(int(i) for i in donate_argnums)
    bad = [i for i in donate if i >= len(main.invars)]
    if bad or n_args != len(main.invars):
        # flattened-arg mismatch (pytree args): positions are ambiguous,
        # refuse to guess rather than mis-report
        report.add(
            "donation", Severity.INFO,
            "cannot map donate_argnums %s onto %d flattened invars — "
            "donation audit skipped (pass flat array arguments)"
            % (sorted(donate), len(main.invars)))
        return
    outset = {id(v) for v in main.outvars}
    used = {id(iv) for eqn in main.eqns for iv in eqn.invars}
    for i in sorted(donate):
        v = main.invars[i]
        if id(v) in outset:
            report.add(
                "donation", Severity.ERROR,
                "donated argument %d is returned unchanged — XLA aliases "
                "the output onto the donated buffer while the caller's "
                "array is invalidated (donation-after-use: any later read "
                "of the input OR the aliased output observes garbage)"
                % i, detail={"argnum": i})
        elif id(v) not in used:
            report.add(
                "donation", Severity.WARNING,
                "donated argument %d is never consumed by the program — "
                "the caller's buffer is destroyed for nothing (drop it "
                "from donate_argnums)" % i, detail={"argnum": i})


# -------------------------------------------------------------- entry points


def analyze_jaxpr(closed_jaxpr, donate_argnums=(), n_args: Optional[int] = None,
                  const_bytes_warn: int = CONST_BYTES_WARN,
                  const_bytes_error: int = CONST_BYTES_ERROR,
                  context: str = "program") -> Report:
    """Run the program passes over an already-traced ``ClosedJaxpr``."""
    report = Report(context=context)
    main = _unwrap_pjit(closed_jaxpr)
    jaxprs = list(_iter_jaxprs(main.jaxpr))
    # the top ClosedJaxpr's consts belong to its own jaxpr's constvars
    jaxprs[0] = (main.jaxpr, list(main.consts))
    _check_baked_consts(report, jaxprs, const_bytes_warn, const_bytes_error)
    _check_f64(report, main.jaxpr, jaxprs)
    _check_host_callbacks(report, jaxprs)
    _check_donation(report, main.jaxpr, donate_argnums,
                    len(main.jaxpr.invars) if n_args is None else n_args)
    return report


def analyze_program(fn, *args, donate_argnums=(),
                    const_bytes_warn: int = CONST_BYTES_WARN,
                    const_bytes_error: int = CONST_BYTES_ERROR,
                    context: str = "program", **kwargs) -> Report:
    """Trace ``fn(*args, **kwargs)`` and audit the captured program.

    ``fn`` may be a plain function, a jitted function (traced through), or
    an already-made ``ClosedJaxpr`` (then ``args`` are ignored). The trace
    is abstract — no FLOPs run, no executable is built.
    """
    import jax
    from jax._src import core as _core

    if isinstance(fn, _core.ClosedJaxpr):
        closed = fn
        n_args = None
    else:
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        n_args = len(jax.tree_util.tree_leaves(args))
    return analyze_jaxpr(closed, donate_argnums=donate_argnums,
                         n_args=n_args, const_bytes_warn=const_bytes_warn,
                         const_bytes_error=const_bytes_error,
                         context=context)

"""Static passes over ``Symbol`` graphs, run pre-bind.

The reference validates graphs in C++ during nnvm InferShape/PlanMemory and
reports failures as engine aborts; here every structural hazard the
two-language design makes statically visible is a named pass producing
:class:`~.findings.Finding`s *before* any XLA compile:

* ``cycle`` — the graph must be a DAG (hand-mutated/composed node lists
  can close a loop; jax would hit Python recursion mid-trace).
* ``dup-name`` — two distinct nodes sharing a name (duplicate Variables
  silently bind ONE buffer to both; duplicate op names collide in
  ``list_outputs``/checkpoint JSON).
* ``dead-node`` / ``unused-input`` — multi-output ops with outputs nothing
  consumes (computed, then thrown away every step) and caller-provided
  bindings that name no graph variable (a typo'd shape dict).
* ``shape-error`` — per-node abstract evaluation with op-contextualized
  errors: the failing node, its op, and its input shapes, instead of the
  raw ``jax.eval_shape`` traceback of the whole graph.
* ``cost-model`` — static per-node FLOP/byte estimates plus a liveness
  memory high-water estimate (params + peak live activations), reported
  as INFO and in ``Report.extras["cost"]``.

Passes degrade gracefully: with no input shapes provided the shape and
cost passes analyze whatever the ``__shape__`` attrs + parameter-shape
derivation can resolve and skip the rest.
"""
from __future__ import annotations

import ast as _pyast
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .findings import Finding, Report, Severity

__all__ = ["analyze_symbol", "GRAPH_PASSES"]


# --------------------------------------------------------------- traversal


def _entry_nodes(sym):
    return [n for n, _ in sym._entries]


def _find_cycle(entries) -> Optional[List[Any]]:
    """Iterative 3-color DFS; returns one cycle's node list or None.
    Must not rely on ``_topo_order`` (which silently tolerates cycles)."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[int, int] = {}
    for root, _ in entries:
        if color.get(id(root), WHITE) != WHITE:
            continue
        stack = [(root, iter([n for n, _ in root.inputs]))]
        color[id(root)] = GRAY
        path = [root]
        while stack:
            node, it = stack[-1]
            child = next(it, None)
            if child is None:
                color[id(node)] = BLACK
                stack.pop()
                path.pop()
                continue
            c = color.get(id(child), WHITE)
            if c == GRAY:
                i = next(i for i, n in enumerate(path)
                         if n is child)
                return path[i:] + [child]
            if c == WHITE:
                color[id(child)] = GRAY
                stack.append((child, iter([n for n, _ in child.inputs])))
                path.append(child)
    return None


# ------------------------------------------------------------ pass context


class GraphContext:
    """Shared state the passes read/populate: the topo node list, resolved
    variable shapes/dtypes, and per-entry output shapes from the node-wise
    abstract evaluation (filled by the shape pass, read by the cost pass)."""

    def __init__(self, sym, input_shapes=None, input_dtypes=None,
                 grad_accum=None, batch_inputs=None):
        from ..symbol.symbol import _topo_order
        self.sym = sym
        self.entries = list(sym._entries)
        self.input_shapes = {k: tuple(v) for k, v in
                             (input_shapes or {}).items()}
        self.input_dtypes = {k: np.dtype(v) for k, v in
                             (input_dtypes or {}).items()}
        # microbatch accumulation factor + the inputs carrying the batch
        # axis (data/label names): the cost model's liveness sweep prices
        # the lax.scan microbatch peak, not the full batch
        self.grad_accum = max(1, int(grad_accum or 1))
        self.batch_inputs = frozenset(batch_inputs or ())
        self.has_cycle = False
        self.nodes = _topo_order(self.entries)
        self.arg_names = sym.list_arguments()
        self.aux_names = sym.list_auxiliary_states()
        # (id(node), out_idx) -> (shape tuple, np.dtype); variables at idx 0
        self.shapes: Dict[Tuple[int, int], Tuple[tuple, Any]] = {}
        self.var_shapes: Dict[str, tuple] = {}

    def resolve_variables(self):
        """Variable shapes: caller-provided > ``__shape__`` attrs >
        structural parameter derivation (the same ladder ``infer_shape``
        climbs — symbol._infer_shapes). The derivation sweep abstract-
        evaluates every node, so it is SKIPPED when the caller already
        provided every shape — the Executor bind hook always does, keeping
        warn/strict binds at one evaluation per node (the shape pass)."""
        resolved = dict(self.input_shapes)
        resolved.pop("__batch_size__", None)
        for node in self.nodes:
            if node.is_variable and node.name not in resolved and \
                    "__shape__" in node.str_attrs:
                try:
                    resolved[node.name] = tuple(
                        _pyast.literal_eval(node.str_attrs["__shape__"]))
                except (ValueError, SyntaxError):
                    pass
        if any(n not in resolved for n in self.arg_names + self.aux_names):
            from ..symbol.symbol import _derive_param_shapes
            try:
                resolved.update(_derive_param_shapes(self.sym, resolved))
            except Exception:                               # noqa: BLE001
                pass  # best-effort; the shape pass reports the gaps
        self.var_shapes = {k: v for k, v in resolved.items()
                           if not any(d == 0 for d in v)}

    def var_dtype(self, node) -> np.dtype:
        if node.name in self.input_dtypes:
            return self.input_dtypes[node.name]
        dt = node.str_attrs.get("__dtype__")
        if dt:
            try:
                return np.dtype(dt)
            except TypeError:
                pass
        return np.dtype(np.float32)


GRAPH_PASSES: List[Tuple[str, Any]] = []


def graph_pass(code):
    def _reg(fn):
        GRAPH_PASSES.append((code, fn))
        return fn
    return _reg


# ------------------------------------------------------------------ passes


@graph_pass("cycle")
def check_cycles(ctx: GraphContext, report: Report) -> None:
    cyc = _find_cycle(ctx.entries)
    if cyc is not None:
        ctx.has_cycle = True
        names = " -> ".join(n.name for n in cyc)
        report.add(
            "cycle", Severity.ERROR,
            "graph contains a cycle (%s) — binding would recurse forever "
            "during tracing" % names,
            node=cyc[0].name, op=getattr(cyc[0].op, "name", "null"))


@graph_pass("dup-name")
def check_duplicate_names(ctx: GraphContext, report: Report) -> None:
    by_name: Dict[str, List[Any]] = {}
    for node in ctx.nodes:
        by_name.setdefault(node.name, []).append(node)
    for name, nodes in by_name.items():
        if len(nodes) < 2:
            continue
        kinds = ["variable" if n.is_variable else n.op.name for n in nodes]
        if all(n.is_variable for n in nodes):
            msg = ("%d distinct Variable nodes named %r — bind maps ONE "
                   "buffer onto all of them and gradients silently merge"
                   % (len(nodes), name))
        else:
            msg = ("%d distinct nodes named %r (%s) — output names and "
                   "checkpoint JSON collide" % (len(nodes), name,
                                                ", ".join(kinds)))
        report.add("dup-name", Severity.ERROR, msg, node=name,
                   op=kinds[0])


@graph_pass("dead-node")
def check_dead_nodes(ctx: GraphContext, report: Report) -> None:
    from ..symbol.symbol import _num_visible_outputs
    consumed = {(id(src), i) for node in ctx.nodes
                for src, i in node.inputs}
    heads = {(id(n), i) for n, i in ctx.entries}
    for node in ctx.nodes:
        if node.is_variable:
            continue
        try:
            n_out = _num_visible_outputs(node)
        except Exception:                                   # noqa: BLE001
            continue
        if n_out < 2:
            # single-output nodes are reachable == consumed by construction
            continue
        dead = [i for i in range(n_out)
                if (id(node), i) not in consumed
                and (id(node), i) not in heads]
        if dead:
            report.add(
                "dead-node", Severity.WARNING,
                "output(s) %s of %d-output op are never consumed — computed "
                "then discarded every run (slice less, or drop the op)"
                % (dead, n_out), node=node.name, op=node.op.name)
    graph_vars = {n.name for n in ctx.nodes if n.is_variable}
    for name in ctx.input_shapes:
        if name != "__batch_size__" and name not in graph_vars:
            report.add(
                "unused-input", Severity.WARNING,
                "provided binding %r names no graph variable (typo, or a "
                "stale shape dict)" % name, node=name)


@graph_pass("shape-error")
def check_shapes(ctx: GraphContext, report: Report) -> None:
    """Node-wise abstract evaluation with shape AND dtype propagation.
    Failures get op-contextualized ERROR findings; successful nodes
    populate ``ctx.shapes`` for the cost model."""
    if ctx.has_cycle:
        return
    import jax

    from ..symbol.symbol import _eval_node_abstract

    ctx.resolve_variables()
    missing = [n for n in ctx.arg_names + ctx.aux_names
               if n not in ctx.var_shapes]
    if missing:
        report.add(
            "shape-error", Severity.INFO,
            "shapes unknown for %s — shape/cost analysis is partial "
            "(pass input_shapes= to analyze, or set Variable(shape=...))"
            % missing[:8])

    def entry_aval(src, i):
        if src.is_variable:
            s = ctx.var_shapes.get(src.name)
            if s is None:
                return None
            return (tuple(s), ctx.var_dtype(src))
        return ctx.shapes.get((id(src), i))

    eval_memo: Dict[tuple, Any] = {}
    for node in ctx.nodes:
        if node.is_variable:
            s = ctx.var_shapes.get(node.name)
            if s is not None:
                ctx.shapes[(id(node), 0)] = (tuple(s), ctx.var_dtype(node))
            continue
        in_avals = [entry_aval(src, i) for src, i in node.inputs]
        if any(a is None for a in in_avals):
            continue
        ckey = (node.op.name, tuple(in_avals),
                tuple(sorted((k, repr(v))
                             for k, v in node.attrs.items())))
        cached = eval_memo.get(ckey)
        if cached is None and ckey not in eval_memo:
            try:
                outs = _eval_node_abstract(
                    node, [jax.ShapeDtypeStruct(s, dt)
                           for s, dt in in_avals])
                cached = tuple((tuple(o.shape), np.dtype(o.dtype))
                               for o in outs)
            except Exception as exc:                        # noqa: BLE001
                cached = exc
            eval_memo[ckey] = cached
        if isinstance(cached, BaseException):
            shapes_str = ", ".join(
                "%s: %s %s" % (src.name, "x".join(map(str, a[0])) or
                               "scalar", a[1])
                for (src, _), a in zip(node.inputs, in_avals))
            report.add(
                "shape-error", Severity.ERROR,
                "op %s rejects its inputs [%s]: %s"
                % (node.op.name, shapes_str,
                   str(cached).splitlines()[0] if str(cached) else
                   type(cached).__name__),
                node=node.name, op=node.op.name,
                detail={"input_shapes": [a[0] for a in in_avals]})
        elif cached is not None:
            for i, aval in enumerate(cached):
                ctx.shapes[(id(node), i)] = aval


# ---------------------------------------------------------------- cost model


def _nelem(shape) -> int:
    return int(np.prod(shape)) if shape else 1


def _attr_bool(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def _attr_tuple(v):
    """Attr values are python tuples from the symbol API but strings
    after a JSON round-trip."""
    if isinstance(v, str):
        try:
            v = _pyast.literal_eval(v)
        except (ValueError, SyntaxError):
            return None
    if v is None:
        return None
    return tuple(v) if isinstance(v, (tuple, list)) else (int(v),)


def _node_flops(node, in_avals, out_avals) -> int:
    """Static FLOP estimate; default one flop per output element
    (elementwise), with explicit rules for the contraction-heavy ops."""
    name = node.op.name
    a = node.attrs
    out_elems = sum(_nelem(s) for s, _ in out_avals)
    try:
        if name == "FullyConnected" and len(in_avals) >= 2:
            k = in_avals[1][0][-1]                 # weight (nh, K)
            return 2 * _nelem(out_avals[0][0]) * int(k)
        if name in ("Convolution", "Convolution_v1") and len(in_avals) >= 2:
            # weight (nf, cin/g, *kernel): each output element needs
            # cin/g * prod(kernel) MACs, so grouped/depthwise conv is
            # priced correctly through the weight shape itself
            w = in_avals[1][0]
            return 2 * _nelem(out_avals[0][0]) * _nelem(w[1:])
        if name == "Deconvolution" and len(in_avals) >= 2:
            # weight (cin, nf/g, *kernel) — NOT the conv layout; pricing
            # through w[1:] would charge nf/g where the contraction depth
            # is cin/g (wrong whenever cin != nf)
            w = in_avals[1][0]
            g = int(a.get("num_group", 1) or 1)
            return 2 * _nelem(out_avals[0][0]) * (int(w[0]) // g) \
                * _nelem(w[2:])
        if name in ("Pooling", "Pooling_v1"):
            # one compare/add per window element per output element (the
            # per-element fallback undercounted by prod(kernel) — the
            # same shape as the PR 6 flash-attention fix); avg adds one
            # divide per output element
            in_shape = in_avals[0][0]
            if _attr_bool(a.get("global_pool")):
                kernel = in_shape[2:]
            else:
                kernel = _attr_tuple(a.get("kernel")) or ()
            out_elems0 = _nelem(out_avals[0][0])
            flops = out_elems0 * max(1, _nelem(kernel))
            if str(a.get("pool_type", "max")) == "avg":
                flops += out_elems0
            return flops
        if name in ("dot", "batch_dot", "linalg_gemm2"):
            k = in_avals[0][0][-1]
            return 2 * _nelem(out_avals[0][0]) * int(k)
        if name == "FlashAttention":
            # fused QK^T + softmax-weighted V: two (T x d)·(d x T)-class
            # contractions per head — 4*T*d FLOPs per output element
            # (q: (..., T, d)); the default one-per-element rule would
            # undercount attention ~15x, skewing the obs_mfu gauge on
            # flash-attention transformers (ISSUE 6 cross-check)
            q_shape = in_avals[0][0]
            t, d = int(q_shape[-2]), int(q_shape[-1])
            return 4 * _nelem(q_shape[:-2]) * t * t * d
        if name == "Embedding":
            return 0                               # a gather, no FLOPs
        if name in ("BatchNorm", "BatchNorm_v1", "LayerNorm",
                    "InstanceNorm", "L2Normalization"):
            return 8 * _nelem(in_avals[0][0])      # mean/var/scale/shift
        if name in ("softmax", "SoftmaxActivation", "SoftmaxOutput",
                    "log_softmax"):
            return 5 * _nelem(in_avals[0][0])
        if name == "RNN":
            T, N, I = in_avals[0][0][:3]
            H = int(a.get("state_size"))
            L = int(a.get("num_layers", 1))
            gates = {"lstm": 4, "gru": 3}.get(a.get("mode", "lstm"), 1)
            return 2 * gates * T * N * (I + H) * H * L
    except (IndexError, KeyError, TypeError, ValueError):
        pass
    return out_elems


def cost_model(ctx: GraphContext, report: Report) -> None:
    """Static per-node FLOPs/bytes + liveness memory high-water. Runs only
    over nodes the shape pass resolved; partial graphs yield partial (but
    still useful) totals, flagged in the summary.

    With ``ctx.grad_accum = N > 1`` the liveness sweep prices what the
    fused step actually materializes: one ``lax.scan`` iteration holds a
    1/N microbatch slice of every batch-leading activation, plus a
    gradient carry (one buffer per grad-bearing param, in the param's
    own dtype — the fused step seeds it with ``zeros_like(param)``)
    alive across the whole scan. FLOPs and bytes_moved stay full-batch —
    the scan runs all N microbatches per step."""
    if ctx.has_cycle:
        return
    # every bound variable buffer (params AND data/label inputs): this is
    # what bind actually allocates and holds live for the whole program
    bound_bytes = 0
    for node in ctx.nodes:
        if node.is_variable and (id(node), 0) in ctx.shapes:
            s, dt = ctx.shapes[(id(node), 0)]
            bound_bytes += _nelem(s) * dt.itemsize

    # microbatching: resolve the batch axis from the declared batch
    # inputs; scaling applies only when every batch input agrees and N
    # divides it (exactly the fused step's own set_grad_accum contract)
    accum = ctx.grad_accum
    batch = None
    if accum > 1 and ctx.batch_inputs:
        leads = set()
        for node in ctx.nodes:
            if node.is_variable and node.name in ctx.batch_inputs:
                aval = ctx.shapes.get((id(node), 0))
                if aval and aval[0]:
                    leads.add(int(aval[0][0]))
        if len(leads) == 1:
            b = leads.pop()
            if b % accum == 0:
                batch = b

    # batch-tainted nodes: everything dataflow-reachable from a batch
    # input. A tainted activation whose element count divides by the
    # batch carries the batch axis SOMEWHERE — leading ((N,T,D)), folded
    # into the lead by reshape ((N*T, D)), or moved inward by transpose
    # ((3, N, H, T, d)) — and shrinks by 1/N inside the scan body.
    # Weight-only intermediates with coincidentally-divisible sizes must
    # NOT shrink (scan-invariant), which is what the taint gate is for;
    # the residue this rule mis-prices is batch REDUCTIONS (tainted,
    # batch axis summed away, size still divisible by luck) — small by
    # construction, and an underestimate only of the scaled-down term.
    tainted = set()
    if batch is not None:
        for node in ctx.nodes:
            if node.is_variable:
                if node.name in ctx.batch_inputs:
                    tainted.add(id(node))
            elif any(id(src) in tainted for src, _ in node.inputs):
                tainted.add(id(node))

    def _live_bytes(node_id, aval) -> int:
        shape, dt = aval
        n = _nelem(shape)
        full = n * dt.itemsize
        if batch is not None and node_id in tainted and n \
                and n % batch == 0:
            return full // accum
        return full

    # the scan's gradient carry: one accumulator per grad-bearing
    # parameter, live for the whole step, priced at the param's own
    # dtype — the fused step's carry is zeros_like(param), NOT an f32
    # upcast (module.py micro_step), so the model must not inflate it
    grad_carry_bytes = 0
    if batch is not None:
        skip = ctx.batch_inputs | frozenset(ctx.aux_names)
        for node in ctx.nodes:
            if node.is_variable and node.name not in skip:
                aval = ctx.shapes.get((id(node), 0))
                if aval is not None:
                    grad_carry_bytes += _nelem(aval[0]) * aval[1].itemsize

    # last topo index consuming each entry; heads live to the end
    order = {id(n): i for i, n in enumerate(ctx.nodes)}
    last_use: Dict[Tuple[int, int], int] = {}
    for node in ctx.nodes:
        for src, i in node.inputs:
            last_use[(id(src), i)] = order[id(node)]
    end = len(ctx.nodes)
    for n, i in ctx.entries:
        last_use[(id(n), i)] = end

    total_flops = 0
    total_bytes = 0
    live = 0
    peak = 0
    skipped = 0
    per_node = []
    # live-set snapshot at the high-water (the graph twin of
    # analyze_program_memory's top_live): what the peak is MADE of —
    # which is what the tuner's remat/accum decisions need to see
    live_entries: Dict[Tuple[int, int], Tuple[str, int]] = {}
    peak_live: List[Tuple[str, int]] = []
    for idx, node in enumerate(ctx.nodes):
        if node.is_variable:
            continue
        in_avals = []
        ok = True
        for src, i in node.inputs:
            aval = ctx.shapes.get((id(src), i))
            if aval is None:
                ok = False
                break
            in_avals.append(aval)
        out_avals = []
        i = 0
        while (id(node), i) in ctx.shapes:
            out_avals.append(ctx.shapes[(id(node), i)])
            i += 1
        if not ok or not out_avals:
            skipped += 1
            continue
        flops = _node_flops(node, in_avals, out_avals)
        in_b = sum(_nelem(s) * dt.itemsize for s, dt in in_avals)
        out_b = sum(_nelem(s) * dt.itemsize for s, dt in out_avals)
        total_flops += flops
        total_bytes += in_b + out_b
        per_node.append((node.name, node.op.name, flops, in_b + out_b))
        # liveness: outputs materialize, then inputs whose last use is
        # this node die (variables/params are counted separately above);
        # under grad_accum only a microbatch slice of each batch-leading
        # activation is live inside the scan body
        for a_i, a in enumerate(out_avals):
            b = _live_bytes(id(node), a)
            live += b
            live_entries[(id(node), a_i)] = (node.name, b)
        if live > peak:
            peak = live
            peak_live = sorted(live_entries.values(),
                               key=lambda t: -t[1])[:10]
        # each dying entry frees ONCE even when consumed through several
        # edges of this node (x*x, concat(x, x))
        dying = {(id(src), i) for src, i in node.inputs
                 if not src.is_variable
                 and last_use.get((id(src), i)) == idx}
        for key in dying:
            aval = ctx.shapes.get(key)
            if aval is not None:
                live -= _live_bytes(key[0], aval)
                live_entries.pop(key, None)

    per_node.sort(key=lambda r: -r[2])
    act_peak = peak + grad_carry_bytes
    cost = {
        "flops": total_flops,
        "bytes_moved": total_bytes,
        "bound_bytes": bound_bytes,
        "peak_bytes": bound_bytes + act_peak,
        "activation_peak_bytes": act_peak,
        "grad_accum": accum,
        "grad_carry_bytes": grad_carry_bytes,
        "nodes_skipped": skipped,
        "top_nodes": [
            {"node": n, "op": o, "flops": f, "bytes": b}
            for n, o, f, b in per_node[:10]],
        "peak_live": [{"node": n, "bytes": b} for n, b in peak_live],
    }
    report.extras["cost"] = cost
    report.add(
        "cost-model", Severity.INFO,
        "%.3g GFLOP, %.3g MB moved, bound buffers %.3g MB, est. peak "
        "memory %.3g MB%s" % (
            total_flops / 1e9, total_bytes / 1e6, bound_bytes / 1e6,
            cost["peak_bytes"] / 1e6,
            " (%d nodes unresolved)" % skipped if skipped else ""),
        detail=cost)


GRAPH_PASSES.append(("cost-model", cost_model))


# -------------------------------------------------------------- entry point


def analyze_symbol(sym, input_shapes=None, input_dtypes=None,
                   passes=None, context: str = "graph",
                   calibrate_remat=None, grad_accum=None,
                   batch_inputs=None) -> Report:
    """Run the graph passes over ``sym``; returns a :class:`Report`.

    ``input_shapes``/``input_dtypes`` play the role of bind-time shapes
    (name -> shape/dtype); omitted names fall back to ``__shape__`` attrs
    and structural parameter derivation. ``passes`` optionally restricts
    to a subset of pass codes. ``calibrate_remat`` forces (True) or
    suppresses (False) the remat pass's concrete block-residual
    calibration; None (default) runs it only when an applied-remat knob
    is active — a plain warn/strict bind analysis must stay
    execution-free (memory_passes._predict_block_savings).
    ``grad_accum=N`` with ``batch_inputs`` (the data/label variable
    names) makes the cost model price the microbatch scan peak instead
    of the full batch — see :func:`cost_model`.
    """
    report = Report(context=context)
    ctx = GraphContext(sym, input_shapes, input_dtypes,
                       grad_accum=grad_accum, batch_inputs=batch_inputs)
    ctx.calibrate_remat = calibrate_remat
    for code, fn in GRAPH_PASSES:
        if passes is not None and code not in passes:
            continue
        fn(ctx, report)
    return report

"""``python -m mxnet_tpu.analysis`` — the analyzer CLI.

Subcommands:

* ``graph <symbol.json | zoo:name>`` — run the graph passes over a saved
  symbol JSON or a model-zoo net (``zoo:resnet18``, ``zoo:mlp``,
  ``zoo:transformer``), with ``--shape name=1,3,224,224`` bindings.
* ``lint <paths...>`` — the AST concurrency/perf lint; ``--baseline``
  fails on drift in either direction (new findings AND stale
  suppressions), ``--write-baseline <file>`` regenerates an arbitrary
  baseline, ``--update-baseline`` regenerates the checked-in CI
  baseline (``tools/analysis_baseline.json`` over ``mxnet_tpu tools``).
* ``audit [targets...]`` — the efficiency auditor (ISSUE 8): memory/
  remat report + roofline classification per zoo net, and the sharding/
  communication audit of the tensor-parallel module on the virtual mesh
  (``tp-mesh`` target, needs 8 devices) plus the cross-island spec
  check. Default targets: ``mlp resnet8 transformer tp-mesh islands``.
* ``self-check`` — the CI gate: model-zoo nets must analyze with zero
  ERROR-level findings.

Exit status: 0 clean, 1 findings at/above the failure threshold
(``--fail-on``, default ERROR for ``graph``/``audit``; any baseline
drift for ``lint``), 2 usage errors.
"""
from __future__ import annotations

import argparse
import os
import sys

from .findings import Severity

__all__ = ["main"]


def _parse_shapes(specs):
    shapes = {}
    for spec in specs or ():
        if "=" not in spec:
            raise SystemExit("--shape expects name=d0,d1,... got %r" % spec)
        name, dims = spec.split("=", 1)
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    return shapes


def _zoo_symbol(name: str):
    """Small-config model-zoo builds: fast to analyze, same op surface as
    the production sizes."""
    from .. import models
    from ..models import transformer as _transformer
    if name.startswith("resnet"):
        layers = int(name[len("resnet"):] or 8)
        return (models.get_resnet(num_classes=10, num_layers=layers,
                                  image_shape="3,32,32"),
                {"data": (2, 3, 32, 32), "softmax_label": (2,)})
    if name == "mlp":
        from ..models import mlp
        return (mlp.get_symbol(num_classes=10),
                {"data": (2, 784), "softmax_label": (2,)})
    if name == "transformer":
        return (_transformer.get_symbol(vocab_size=128, num_layers=2,
                                        d_model=32, n_heads=2, seq_len=16),
                {"data": (2, 16), "softmax_label": (2, 16)})
    raise SystemExit("unknown zoo model %r (try resnet8, resnet20, mlp, "
                     "transformer)" % name)


def _cmd_graph(args) -> int:
    from . import analyze_symbol
    if args.target.startswith("zoo:"):
        sym, shapes = _zoo_symbol(args.target[4:])
        shapes.update(_parse_shapes(args.shape))
    else:
        from ..symbol import load
        sym = load(args.target)
        shapes = _parse_shapes(args.shape)
    report = analyze_symbol(sym, input_shapes=shapes or None,
                            context=args.target)
    print(report.format(min_severity=Severity[args.min_severity]))
    fail_at = Severity[args.fail_on]
    return 1 if report.at_least(fail_at) else 0


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _cmd_lint(args) -> int:
    from . import (diff_baseline, lint_paths, load_baseline,
                   stale_baseline, write_baseline)
    if args.update_baseline:
        # regenerate the CHECKED-IN CI baseline with its canonical
        # paths/root, so "fix the drift" is one copy-pasteable command
        root = _repo_root()
        paths = [os.path.join(root, "mxnet_tpu"),
                 os.path.join(root, "tools")]
        target = os.path.join(root, "tools", "analysis_baseline.json")
        report = lint_paths(paths)
        n_keys = write_baseline(report, target, root)
        print("updated %s: %d finding key(s) (%d finding(s))"
              % (target, n_keys, len(report)))
        return 0
    if not args.paths:
        # usage error -> 2, per the module contract (SystemExit with a
        # string message would exit 1 — indistinguishable from drift)
        print("lint needs paths (or --update-baseline)", file=sys.stderr)
        return 2
    root = os.path.abspath(args.root)
    report = lint_paths(args.paths)
    if args.write_baseline:
        n_keys = write_baseline(report, args.write_baseline, root)
        print("wrote %d finding key(s) (%d finding(s)) to %s"
              % (n_keys, len(report), args.write_baseline))
        return 0
    if args.baseline:
        baseline = load_baseline(args.baseline)
        fresh = diff_baseline(report, baseline, root)
        stale = stale_baseline(report, baseline, root)
        known = len(report) - len(fresh)
        if not fresh and not stale:
            print("lint: no baseline drift (%d baselined)" % known)
            return 0
        if fresh:
            print("lint: %d NEW finding(s) (%d baselined):"
                  % (len(fresh), known))
            for f in fresh:
                print(f.format())
        if stale:
            print("lint: %d STALE baseline suppression(s) — the debt "
                  "was paid off; run `python -m mxnet_tpu.analysis lint "
                  "--update-baseline` so the next real finding at these "
                  "keys is not masked:" % len(stale))
            for k, excess in stale.items():
                print("  %s (x%d)" % (k, excess))
        return 1
    print(report.format())
    return 1 if report.findings else 0


# ------------------------------------------------------------------ audit


def _audit_zoo_net(name: str, fail_at) -> int:
    """Memory/remat + roofline audit of one zoo net; returns 1 on
    findings at/above ``fail_at``."""
    import jax
    from . import analyze_symbol, roofline
    from .findings import Severity as S
    if name.startswith("zoo:"):
        name = name[4:]           # accept the graph subcommand's spelling
    sym, shapes = _zoo_symbol(name)
    # audits always calibrate the remat prediction (one block forward +
    # vjp on zeros) — this is the offline path where that cost belongs
    report = analyze_symbol(sym, input_shapes=shapes, context=name,
                            calibrate_remat=True)
    cost = report.extras.get("cost", {})
    remat = report.extras.get("remat", {})
    print("== %s: %.3g GFLOP, est peak %.3g MB (%.3g MB activations)"
          % (name, cost.get("flops", 0) / 1e9,
             cost.get("peak_bytes", 0) / 1e6,
             cost.get("activation_peak_bytes", 0) / 1e6))
    sug = remat.get("suggestion")
    if sug:
        cands = remat.get("candidates", [])
        print("   remat: %d candidate(s), ~%.3g MB recoverable; top: %s"
              % (len(cands), sug["est_bytes_saved"] / 1e6,
                 ", ".join("%s(%s, %.3g MB)"
                           % (c["node"], c["op"], c["bytes"] / 1e6)
                           for c in cands[:3])))
        print("   suggestion: %s" % sug["hint"])
    else:
        print("   remat: no candidates")
    # roofline: compile the bound forward and reconcile with the model
    try:
        from ..context import cpu
        ex = sym.simple_bind(cpu(), **shapes)
        key = jax.random.PRNGKey(0)
        args = {n: a.data for n, a in ex.arg_dict.items()}
        aux = {n: a.data for n, a in ex.aux_dict.items()}
        roofline.analyze_executable(
            lambda a, x: ex._fn(a, x, key, False)[0], args, aux,
            model_flops=float(cost.get("flops") or 0) or None,
            context=name, report=report)
        roof = report.extras.get("roofline", {})
        cls = ("%s-bound, attainable MFU %.2f"
               % (roof["bound"], roof["attainable_mfu"])
               if "bound" in roof else "roofline unknown "
               "(set MXNET_TPU_OBS_PEAK_FLOPS/MXNET_TPU_ANALYZE_HBM_GBPS)")
        print("   roofline: compiled %.3g GFLOP vs model %.3g GFLOP "
              "(ratio %s); %s"
              % (roof.get("compiled_flops", 0) / 1e9,
                 cost.get("flops", 0) / 1e9,
                 roof.get("model_ratio", "n/a"), cls))
    except Exception as exc:                                # noqa: BLE001
        print("   roofline: unavailable (%s: %s)"
              % (type(exc).__name__,
                 (str(exc).splitlines() or [""])[0][:100]))
    for f in report.at_least(S.WARNING):
        print("   " + f.format())
    return 1 if report.at_least(fail_at) else 0


def _audit_tp_mesh(fail_at) -> int:
    """Sharding/communication audit of the Megatron-style TP module on
    the 8-device virtual mesh (the MULTICHIP dryrun twin)."""
    import jax
    from . import analyze_module_sharding
    from .findings import Severity as S
    if len(jax.devices()) < 8:
        print("== tp-mesh: SKIPPED (needs 8 devices; run under "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        return 0
    from .. import symbol as sym_mod
    from ..context import cpu
    from ..initializer import Uniform
    from ..module import Module
    from jax.sharding import PartitionSpec as P

    data = sym_mod.Variable("data")
    h = sym_mod.FullyConnected(data, num_hidden=32, name="fc1")
    h = sym_mod.Activation(h, act_type="tanh")
    h = sym_mod.FullyConnected(h, num_hidden=2, name="fc2")
    net = sym_mod.SoftmaxOutput(h, name="softmax")
    # Megatron split: fc1 column-parallel, fc2 row-parallel — exactly
    # one all-reduce over `model` in the forward (fc2's contraction)
    mod = Module(net, context=[cpu(i) for i in range(8)],
                 mesh_shape={"data": 2, "model": 4},
                 param_shardings={"fc1_weight": P("model", None),
                                  "fc1_bias": P("model"),
                                  "fc2_weight": P(None, "model")})
    mod.bind(data_shapes=[("data", (64, 6))],
             label_shapes=[("softmax_label", (64,))])
    mod.init_params(Uniform(0.01))
    report = analyze_module_sharding(mod)
    comm = report.extras.get("comm", {})
    print("== tp-mesh (data=2 x model=4, Megatron MLP):")
    for axis, agg in sorted(comm.get("per_axis", {}).items()):
        print("   axis %-14s %d collective(s), %.3g KB buffers, "
              "%.3g KB on links, ~%.3g us"
              % (axis, agg["count"], agg["bytes"] / 1e3,
                 agg["link_bytes"] / 1e3, agg["est_us"]))
    if not comm.get("collectives"):
        print("   (no collectives found)")
    for f in report.at_least(S.WARNING):
        print("   " + f.format())
    return 1 if report.at_least(fail_at) else 0


def _audit_islands(fail_at) -> int:
    """Cross-island spec audit: every parallel mode's canonical layout
    claims against the canonical ``data x fsdp x tp`` mesh. Since the
    SpecLayout unification (ROADMAP item 1) this must report ZERO
    disagreements — any finding here is an island drifting from the
    unified layout, and the audit exits 1 on it."""
    import jax
    from . import check_islands
    from ..parallel import sharding_islands
    from ..parallel.layout import SpecLayout
    islands = sharding_islands()
    mesh = None
    if len(jax.devices()) >= 8:
        mesh = SpecLayout(data=2, fsdp=2, tp=2).mesh()
    report = check_islands(islands, mesh=mesh, context="islands")
    status = "unified (zero disagreements)" if not report.findings else \
        "%d finding(s) — an island drifted from the unified SpecLayout" \
        % len(report)
    print("== islands: %d island(s), %s" % (len(islands), status))
    for f in report:
        print("   " + f.format())
    # ANY cross-island finding is a unification regression, not merely
    # advisory — fail the audit on WARNING-level findings here
    return 1 if report.findings else 0


def _cmd_audit(args) -> int:
    fail_at = Severity[args.fail_on]
    targets = args.targets or ["mlp", "resnet8", "transformer", "tp-mesh",
                               "islands"]
    failed = 0
    for t in targets:
        if t == "tp-mesh":
            failed += _audit_tp_mesh(fail_at)
        elif t == "islands":
            failed += _audit_islands(fail_at)
        else:
            try:
                failed += _audit_zoo_net(t, fail_at)
            except SystemExit as exc:
                # a mistyped target is a USAGE error (exit 2), not an
                # audit failure (exit 1) — CI keys on the distinction
                print(exc, file=sys.stderr)
                return 2
    return 1 if failed else 0


def _cmd_self_check(args) -> int:
    """Model-zoo nets must produce zero ERROR-level graph findings — the
    analyzer's own regression gate (a pass that starts mis-firing on known
    -good nets fails CI here, not in user binds) — plus the async-loop
    counter gate: a small async ``fit()`` must do ZERO per-batch host
    syncs and ZERO steady-state recompiles (the loop_* profiler counters
    the fit pipeline reports, docs/architecture/async_loop.md)."""
    from . import analyze_symbol
    failed = 0
    for name in ("resnet8", "mlp", "transformer"):
        sym, shapes = _zoo_symbol(name)
        report = analyze_symbol(sym, input_shapes=shapes, context=name)
        errs = report.errors
        status = "FAIL (%d errors)" % len(errs) if errs else "ok"
        cost = report.extras.get("cost", {})
        print("%-12s %-18s %.3g GFLOP, est peak %.3g MB"
              % (name, status, cost.get("flops", 0) / 1e9,
                 cost.get("peak_bytes", 0) / 1e6))
        for f in errs:
            print("  " + f.format())
        failed += bool(errs)
    failed += _async_loop_counter_check()
    return 1 if failed else 0


def _async_loop_counter_check() -> int:
    """One tiny async fit(); the loop counters must show a clean pipeline:
    0 per-batch host syncs, 0 steady-state recompiles, every batch fed by
    the device-prefetch stage."""
    import numpy as np
    from .. import config, io, module, profiler, symbol
    from ..initializer import Uniform

    data = symbol.Variable("data")
    fc = symbol.FullyConnected(data, num_hidden=8, name="fc1")
    net = symbol.SoftmaxOutput(fc, name="softmax")
    rng = np.random.RandomState(0)
    it = io.NDArrayIter(rng.uniform(-1, 1, (48, 16)).astype(np.float32),
                        rng.randint(0, 8, (48,)).astype(np.float32),
                        batch_size=8)
    from ..context import cpu
    mod = module.Module(net, context=cpu())
    # pin every loop knob: the gate asserts exact counter values, and an
    # ambient MXNET_TPU_DEVICE_PREFETCH=0 (say) would fail the check on
    # healthy code — the check targets the code, not the environment
    knobs = {"MXNET_TPU_ASYNC_WINDOW": 2, "MXNET_TPU_DEVICE_PREFETCH": 2,
             "MXNET_TPU_DEVICE_METRICS": True}
    for k, v in knobs.items():
        config.set(k, v)
    try:
        with profiler.counter_delta() as d:
            mod.fit(it, eval_metric="acc", num_epoch=2, optimizer="sgd",
                    initializer=Uniform(0.01),
                    optimizer_params={"learning_rate": 0.1})
        c = d.all()
    finally:
        for k in knobs:
            config.reset(k)
    checks = (
        ("loop_host_sync", c.get("loop_host_sync", 0), 0),
        ("loop_recompile", c.get("loop_recompile", 0), 0),
        ("loop_prefetch_placed", c.get("loop_prefetch_placed", 0), 12),
    )
    bad = [(k, got, want) for k, got, want in checks if got != want]
    status = "FAIL %s" % bad if bad else "ok"
    print("%-12s %-18s async fit counters: %s" % ("async-loop", status,
          {k: v for k, v in sorted(c.items()) if k.startswith("loop_")}))
    return 1 if bad else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m mxnet_tpu.analysis",
                                description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("graph", help="graph passes over a symbol")
    g.add_argument("target", help="symbol JSON path or zoo:<name>")
    g.add_argument("--shape", action="append",
                   help="input shape binding name=d0,d1,... (repeatable)")
    g.add_argument("--min-severity", default="INFO",
                   choices=[s.name for s in Severity])
    g.add_argument("--fail-on", default="ERROR",
                   choices=[s.name for s in Severity])
    g.set_defaults(fn=_cmd_graph)

    l = sub.add_parser("lint", help="AST concurrency/perf lint")
    l.add_argument("paths", nargs="*")
    l.add_argument("--baseline", help="fail on drift vs this baseline "
                                      "JSON (new findings AND stale "
                                      "suppressions)")
    l.add_argument("--write-baseline", help="regenerate the baseline file "
                                            "and exit 0")
    l.add_argument("--update-baseline", action="store_true",
                   help="regenerate the checked-in CI baseline "
                        "(tools/analysis_baseline.json over "
                        "mxnet_tpu+tools) and exit 0")
    l.add_argument("--root", default=".",
                   help="path findings are keyed relative to (default .)")
    l.set_defaults(fn=_cmd_lint)

    a = sub.add_parser("audit",
                       help="efficiency audit: memory/remat + roofline "
                            "per zoo net, sharding/comm on the virtual "
                            "mesh")
    a.add_argument("targets", nargs="*",
                   help="zoo:<name> style targets plus tp-mesh/islands "
                        "(default: mlp resnet8 transformer tp-mesh "
                        "islands)")
    a.add_argument("--fail-on", default="ERROR",
                   choices=[s.name for s in Severity])
    a.set_defaults(fn=_cmd_audit)

    s = sub.add_parser("self-check",
                       help="model zoo must analyze with zero ERRORs")
    s.set_defaults(fn=_cmd_self_check)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

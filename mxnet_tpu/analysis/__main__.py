"""``python -m mxnet_tpu.analysis`` — the analyzer CLI.

Subcommands:

* ``graph <symbol.json | zoo:name>`` — run the graph passes over a saved
  symbol JSON or a model-zoo net (``zoo:resnet18``, ``zoo:mlp``,
  ``zoo:transformer``), with ``--shape name=1,3,224,224`` bindings.
* ``lint <paths...>`` — the AST concurrency/perf lint; ``--baseline``
  fails only on findings NOT in the baseline file, ``--write-baseline``
  regenerates it.
* ``self-check`` — the CI gate: model-zoo nets must analyze with zero
  ERROR-level findings.

Exit status: 0 clean, 1 findings at/above the failure threshold
(``--fail-on``, default ERROR for ``graph``; any non-baseline finding for
``lint``), 2 usage errors.
"""
from __future__ import annotations

import argparse
import os
import sys

from .findings import Severity

__all__ = ["main"]


def _parse_shapes(specs):
    shapes = {}
    for spec in specs or ():
        if "=" not in spec:
            raise SystemExit("--shape expects name=d0,d1,... got %r" % spec)
        name, dims = spec.split("=", 1)
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    return shapes


def _zoo_symbol(name: str):
    """Small-config model-zoo builds: fast to analyze, same op surface as
    the production sizes."""
    from .. import models
    from ..models import transformer as _transformer
    if name.startswith("resnet"):
        layers = int(name[len("resnet"):] or 8)
        return (models.get_resnet(num_classes=10, num_layers=layers,
                                  image_shape="3,32,32"),
                {"data": (2, 3, 32, 32), "softmax_label": (2,)})
    if name == "mlp":
        from ..models import mlp
        return (mlp.get_symbol(num_classes=10),
                {"data": (2, 784), "softmax_label": (2,)})
    if name == "transformer":
        return (_transformer.get_symbol(vocab_size=128, num_layers=2,
                                        d_model=32, n_heads=2, seq_len=16),
                {"data": (2, 16), "softmax_label": (2, 16)})
    raise SystemExit("unknown zoo model %r (try resnet8, resnet20, mlp, "
                     "transformer)" % name)


def _cmd_graph(args) -> int:
    from . import analyze_symbol
    if args.target.startswith("zoo:"):
        sym, shapes = _zoo_symbol(args.target[4:])
        shapes.update(_parse_shapes(args.shape))
    else:
        from ..symbol import load
        sym = load(args.target)
        shapes = _parse_shapes(args.shape)
    report = analyze_symbol(sym, input_shapes=shapes or None,
                            context=args.target)
    print(report.format(min_severity=Severity[args.min_severity]))
    fail_at = Severity[args.fail_on]
    return 1 if report.at_least(fail_at) else 0


def _cmd_lint(args) -> int:
    from . import diff_baseline, lint_paths, load_baseline, write_baseline
    root = os.path.abspath(args.root)
    report = lint_paths(args.paths)
    if args.write_baseline:
        n_keys = write_baseline(report, args.write_baseline, root)
        print("wrote %d finding key(s) (%d finding(s)) to %s"
              % (n_keys, len(report), args.write_baseline))
        return 0
    if args.baseline:
        fresh = diff_baseline(report, load_baseline(args.baseline), root)
        known = len(report) - len(fresh)
        if not fresh:
            print("lint: no new findings (%d baselined)" % known)
            return 0
        print("lint: %d NEW finding(s) (%d baselined):" % (len(fresh),
                                                           known))
        for f in fresh:
            print(f.format())
        return 1
    print(report.format())
    return 1 if report.findings else 0


def _cmd_self_check(args) -> int:
    """Model-zoo nets must produce zero ERROR-level graph findings — the
    analyzer's own regression gate (a pass that starts mis-firing on known
    -good nets fails CI here, not in user binds) — plus the async-loop
    counter gate: a small async ``fit()`` must do ZERO per-batch host
    syncs and ZERO steady-state recompiles (the loop_* profiler counters
    the fit pipeline reports, docs/architecture/async_loop.md)."""
    from . import analyze_symbol
    failed = 0
    for name in ("resnet8", "mlp", "transformer"):
        sym, shapes = _zoo_symbol(name)
        report = analyze_symbol(sym, input_shapes=shapes, context=name)
        errs = report.errors
        status = "FAIL (%d errors)" % len(errs) if errs else "ok"
        cost = report.extras.get("cost", {})
        print("%-12s %-18s %.3g GFLOP, est peak %.3g MB"
              % (name, status, cost.get("flops", 0) / 1e9,
                 cost.get("peak_bytes", 0) / 1e6))
        for f in errs:
            print("  " + f.format())
        failed += bool(errs)
    failed += _async_loop_counter_check()
    return 1 if failed else 0


def _async_loop_counter_check() -> int:
    """One tiny async fit(); the loop counters must show a clean pipeline:
    0 per-batch host syncs, 0 steady-state recompiles, every batch fed by
    the device-prefetch stage."""
    import numpy as np
    from .. import config, io, module, profiler, symbol
    from ..initializer import Uniform

    data = symbol.Variable("data")
    fc = symbol.FullyConnected(data, num_hidden=8, name="fc1")
    net = symbol.SoftmaxOutput(fc, name="softmax")
    rng = np.random.RandomState(0)
    it = io.NDArrayIter(rng.uniform(-1, 1, (48, 16)).astype(np.float32),
                        rng.randint(0, 8, (48,)).astype(np.float32),
                        batch_size=8)
    from ..context import cpu
    mod = module.Module(net, context=cpu())
    # pin every loop knob: the gate asserts exact counter values, and an
    # ambient MXNET_TPU_DEVICE_PREFETCH=0 (say) would fail the check on
    # healthy code — the check targets the code, not the environment
    knobs = {"MXNET_TPU_ASYNC_WINDOW": 2, "MXNET_TPU_DEVICE_PREFETCH": 2,
             "MXNET_TPU_DEVICE_METRICS": True}
    for k, v in knobs.items():
        config.set(k, v)
    try:
        with profiler.counter_delta() as d:
            mod.fit(it, eval_metric="acc", num_epoch=2, optimizer="sgd",
                    initializer=Uniform(0.01),
                    optimizer_params={"learning_rate": 0.1})
        c = d.all()
    finally:
        for k in knobs:
            config.reset(k)
    checks = (
        ("loop_host_sync", c.get("loop_host_sync", 0), 0),
        ("loop_recompile", c.get("loop_recompile", 0), 0),
        ("loop_prefetch_placed", c.get("loop_prefetch_placed", 0), 12),
    )
    bad = [(k, got, want) for k, got, want in checks if got != want]
    status = "FAIL %s" % bad if bad else "ok"
    print("%-12s %-18s async fit counters: %s" % ("async-loop", status,
          {k: v for k, v in sorted(c.items()) if k.startswith("loop_")}))
    return 1 if bad else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m mxnet_tpu.analysis",
                                description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("graph", help="graph passes over a symbol")
    g.add_argument("target", help="symbol JSON path or zoo:<name>")
    g.add_argument("--shape", action="append",
                   help="input shape binding name=d0,d1,... (repeatable)")
    g.add_argument("--min-severity", default="INFO",
                   choices=[s.name for s in Severity])
    g.add_argument("--fail-on", default="ERROR",
                   choices=[s.name for s in Severity])
    g.set_defaults(fn=_cmd_graph)

    l = sub.add_parser("lint", help="AST concurrency/perf lint")
    l.add_argument("paths", nargs="+")
    l.add_argument("--baseline", help="fail only on findings not in this "
                                      "baseline JSON")
    l.add_argument("--write-baseline", help="regenerate the baseline file "
                                            "and exit 0")
    l.add_argument("--root", default=".",
                   help="path findings are keyed relative to (default .)")
    l.set_defaults(fn=_cmd_lint)

    s = sub.add_parser("self-check",
                       help="model zoo must analyze with zero ERRORs")
    s.set_defaults(fn=_cmd_self_check)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

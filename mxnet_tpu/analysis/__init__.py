"""``mxnet_tpu.analysis`` — static graph/program analysis.

Three analyzers over the two-language design (ISSUE 3; see
``docs/architecture/analysis.md``):

* :func:`analyze_symbol` — graph passes over ``Symbol`` DAGs run pre-bind
  (cycle / dup-name / dead-node / shape-error / cost-model). Also exposed
  as ``Symbol.analyze()`` and ``Module.analyze()``.
* :func:`analyze_program` — jaxpr hazard checks run post-trace
  (baked-const / f64-promotion / host-callback / donation).
* :func:`lint_paths` — AST concurrency/perf lint for the codebase itself
  (lock-host-sync / lock-dispatch / wall-clock), with inline
  ``# mx-lint: allow(code)`` suppressions and a CI baseline.

Bind-time enforcement rides the ``MXNET_TPU_ANALYZE=off|warn|strict`` knob
(:func:`check_bind`, called from ``Executor.__init__``): ``warn`` logs
WARNING+ findings, ``strict`` raises ``MXNetError`` on ERROR findings.
The knob defaults to ``off`` and the Executor hook imports this package
lazily, so analysis is strictly zero-cost when disabled (asserted by
``tests/test_analysis.py::test_analyze_off_is_zero_cost``).

Every finding increments an always-on profiler counter for its hazard
class (``analysis_<code>``), so hazard rates are observable fleet-wide
without holding Report objects.

CLI: ``python -m mxnet_tpu.analysis {graph,lint,self-check} ...``.
"""
from __future__ import annotations

from .findings import Finding, Report, Severity
from .graph_passes import GRAPH_PASSES, analyze_symbol
from .program_passes import analyze_jaxpr, analyze_program
from .lint import (baseline_key, diff_baseline, lint_paths, lint_source,
                   load_baseline, write_baseline)

__all__ = [
    "Finding", "Report", "Severity",
    "analyze_symbol", "analyze_program", "analyze_jaxpr",
    "lint_paths", "lint_source",
    "load_baseline", "write_baseline", "diff_baseline", "baseline_key",
    "check_bind", "GRAPH_PASSES",
]


def check_bind(symbol, input_shapes=None, input_dtypes=None,
               mode: str = "warn", context: str = "bind") -> Report:
    """The bind-time verification hook (``MXNET_TPU_ANALYZE``): run the
    graph passes with the bind's shapes and enforce the mode contract —
    ``warn`` logs, ``strict`` raises on ERROR findings. Returns the Report
    so callers (tests, tools) can inspect what fired."""
    report = analyze_symbol(symbol, input_shapes=input_shapes,
                            input_dtypes=input_dtypes, context=context)
    return report.enforce(mode)

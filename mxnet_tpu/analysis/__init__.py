"""``mxnet_tpu.analysis`` — static graph/program/efficiency analysis.

Analyzers over the two-language design (ISSUE 3 + ISSUE 8; see
``docs/architecture/analysis.md``), all sharing one
Finding/Report/Severity vocabulary:

* :func:`analyze_symbol` — graph passes over ``Symbol`` DAGs run pre-bind
  (cycle / dup-name / dead-node / shape-error / cost-model), now joined
  by the **memory passes** (``remat-opportunity`` ranking long-lived
  cheap-to-recompute activations with concrete ``jax.checkpoint``
  policy suggestions, and the enforceable ``hbm-budget`` —
  ``MXNET_TPU_ANALYZE_HBM_BUDGET``). Also exposed as
  ``Symbol.analyze()`` and ``Module.analyze()``.
* :func:`analyze_program` — jaxpr hazard checks run post-trace
  (baked-const / f64-promotion / host-callback / donation);
  :func:`analyze_program_memory` — hierarchical jaxpr liveness
  (activation high-water per program, the metric remat suggestions
  move).
* :mod:`.sharding_passes` — spec audits against a mesh
  (``spec-axis``/``spec-rank``/``reshard-thrash``/``fsdp-opportunity``)
  and the post-partitioning HLO collective walk with the static
  comm-bytes/link-time cost model (``Report.extras["comm"]``).
* :mod:`.roofline` — compiled-cost (``compiled.cost_analysis()``) vs
  the analysis FLOP model (``flop-model-drift``), compute- vs
  memory-bound classification, and the ``mx.obs.report()``
  reconciliation that puts a "why" next to every ``obs_mfu`` number.
* :func:`lint_paths` — AST concurrency/perf lint for the codebase itself
  (lock-host-sync / lock-dispatch / wall-clock / eager-loop-sync /
  signal-unsafe), with inline ``# mx-lint: allow(code)`` suppressions
  and a CI baseline that fails on drift in either direction.
* :mod:`.concurrency` — the whole-program lock-order pass riding the
  same lint entry points: names every lock object in the package,
  builds the acquires-while-holding graph with calls resolved one
  level through package-local helpers, and reports
  ``lock-order-cycle`` (ERROR, both chains with file:line),
  interprocedural ``lock-host-sync`` (a helper syncing under the
  caller's lock), and ``unlocked-shared-state`` (WARNING). Its runtime
  twin is ``mxnet_tpu.lockcheck`` (``MXNET_TPU_LOCKCHECK=off|warn|
  abort``), which witnesses the ACTUAL acquisition order online.

Bind-time enforcement rides the ``MXNET_TPU_ANALYZE=off|warn|strict`` knob
(:func:`check_bind`, called from ``Executor.__init__``): ``warn`` logs
WARNING+ findings, ``strict`` raises ``MXNetError`` on ERROR findings —
including an over-``MXNET_TPU_ANALYZE_HBM_BUDGET`` bind, rejected before
any trace or compile. The knob defaults to ``off`` and the Executor hook
imports this package lazily, so analysis is strictly zero-cost when
disabled (asserted by ``tests/test_analysis.py::test_analyze_off_is_zero_cost``).

Every finding increments an always-on profiler counter for its hazard
class (``analysis_<code>``), so hazard rates are observable fleet-wide
without holding Report objects.

CLI: ``python -m mxnet_tpu.analysis {graph,lint,audit,self-check} ...``.
"""
from __future__ import annotations

from .findings import Finding, Report, Severity
from .graph_passes import GRAPH_PASSES, analyze_symbol
# importing memory_passes registers remat-opportunity + hbm-budget into
# GRAPH_PASSES (after the cost model they read)
from .memory_passes import analyze_program_memory, parse_bytes
from .program_passes import analyze_jaxpr, analyze_program
from .lint import (baseline_key, diff_baseline, lint_paths, lint_source,
                   load_baseline, stale_baseline, write_baseline)
from .concurrency import analyze_sources
from . import concurrency, memory_passes, roofline, sharding_passes
from . import tuning
from .sharding_passes import (analyze_collectives, analyze_module_sharding,
                              check_islands, check_replicated, check_specs)

__all__ = [
    "Finding", "Report", "Severity",
    "analyze_symbol", "analyze_program", "analyze_jaxpr",
    "analyze_program_memory", "parse_bytes",
    "analyze_collectives", "analyze_module_sharding",
    "check_specs", "check_islands", "check_replicated",
    "memory_passes", "sharding_passes", "roofline", "concurrency",
    "tuning",
    "lint_paths", "lint_source", "analyze_sources",
    "load_baseline", "write_baseline", "diff_baseline", "stale_baseline",
    "baseline_key",
    "check_bind", "GRAPH_PASSES",
]


def check_bind(symbol, input_shapes=None, input_dtypes=None,
               mode: str = "warn", context: str = "bind") -> Report:
    """The bind-time verification hook (``MXNET_TPU_ANALYZE``): run the
    graph passes (structural + cost + memory/budget) with the bind's
    shapes and enforce the mode contract — ``warn`` logs, ``strict``
    raises on ERROR findings. Returns the Report so callers (tests,
    tools) can inspect what fired."""
    report = analyze_symbol(symbol, input_shapes=input_shapes,
                            input_dtypes=input_dtypes, context=context)
    return report.enforce(mode)

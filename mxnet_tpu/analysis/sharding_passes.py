"""Sharding/communication passes: spec audits + a static comm cost model.

ROADMAP item 1's risk is spending a 6000-chip bill to discover a bad
layout; these passes make layouts auditable at bind time on the 8-device
virtual mesh. Three layers:

* **Spec audits** (no tracing): :func:`check_specs` validates a
  ``name -> PartitionSpec`` map against a mesh and the array shapes
  (unknown axes, over-ranked specs, non-dividing dims);
  :func:`check_islands` compares the *separate sharding islands*
  (``parallel/{mesh,dist,moe,pipeline,ring_attention}.py`` each declare
  their canonical specs via ``parallel.sharding_islands()``) for the two
  cross-island hazards — an axis an island partitions over that the
  bound mesh does not carry, and the same logical array declared with
  different layouts in different islands (**resharding thrash**: every
  boundary crossing pays an all-to-all);
  :func:`check_replicated` flags large fully-replicated parameters as
  FSDP opportunities with the bytes a sharded layout recovers per
  device.
* **Collective walk** (:func:`analyze_collectives`): jit + lower +
  compile the program against its shardings, then walk the
  post-partitioning HLO for ``all-reduce`` / ``all-gather`` /
  ``reduce-scatter`` / ``all-to-all`` / ``collective-permute`` ops,
  attribute each to its mesh axis by matching ``replica_groups`` against
  the axis subgroups, and apply the static ring-cost model
  (:func:`comm_link_bytes`) with the ICI bandwidth table to estimate
  per-axis link time. ``Report.extras["comm"]`` is the machine-readable
  table; the acceptance test hand-computes one known collective's bytes
  against it.
* **Module audit** (:func:`analyze_module_sharding`): all of the above
  for a mesh-bound ``Module`` — specs resolved exactly as the bind path
  resolves them (``Module._sharding_for``), the program being the bound
  executor's forward.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .findings import Report, Severity

__all__ = ["check_specs", "check_islands", "check_replicated",
           "analyze_collectives", "analyze_module_sharding",
           "collectives_from_hlo", "comm_link_bytes",
           "device_table_lookup",
           "FSDP_MIN_BYTES", "ICI_GBPS_BY_DEVICE_KIND"]

# replicated params smaller than this are not worth sharding (the
# all-gather latency beats the HBM savings)
FSDP_MIN_BYTES = 1 << 20            # 1 MiB

# per-link ICI bandwidth (GB/s, one direction) by TPU generation — the
# static cost model's time axis. A model, not a measurement: good enough
# to rank layouts and spot an axis that moves 100x the bytes of another.
ICI_GBPS_BY_DEVICE_KIND = [
    ("v5p", 100.0), ("v5 lite", 50.0), ("v5e", 50.0),
    ("v6", 100.0), ("v4", 50.0), ("v3", 70.0), ("v2", 70.0)]
_DEFAULT_ICI_GBPS = 50.0

_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<kind>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?P<start>-start)?\(")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
# XLA's iota (V2) format: replica_groups=[2,4]<=[8] or ...<=[4,2]T(1,0)
_GROUPS_V2_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _spec_parts(spec) -> List[Any]:
    """PartitionSpec -> list of per-dim entries (None | axis | tuple)."""
    if spec is None:
        return []
    return list(spec)


def _spec_axes(spec) -> List[str]:
    axes: List[str] = []
    for part in _spec_parts(spec):
        if part is None:
            continue
        axes.extend(part if isinstance(part, (tuple, list)) else [part])
    return axes


# ------------------------------------------------------------- spec audits


def check_specs(mesh, specs: Dict[str, Any],
                shapes: Optional[Dict[str, Sequence[int]]] = None,
                report: Optional[Report] = None,
                context: str = "sharding") -> Report:
    """Validate ``name -> PartitionSpec`` against ``mesh`` (+shapes).

    * ``spec-axis`` (ERROR) — a spec partitions over an axis the mesh
      does not carry: GSPMD rejects it at trace time on the big job;
      here it is a finding at audit time.
    * ``spec-rank`` (ERROR) — spec has more entries than the array has
      dims.
    * ``spec-divisibility`` (WARNING) — the axis size does not divide
      the dim: XLA pads every shard (wasted HBM + compute on the pad).
    * ``spec-duplicate-axis`` (ERROR) — one axis partitions two dims of
      the same array (invalid).
    """
    report = report if report is not None else Report(context=context)
    mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape)) \
        if mesh is not None else {}
    for name, spec in sorted(specs.items()):
        parts = _spec_parts(spec)
        axes = _spec_axes(spec)
        for ax in axes:
            if mesh_axes and ax not in mesh_axes:
                report.add(
                    "spec-axis", Severity.ERROR,
                    "spec %s for %r partitions over axis %r but the mesh "
                    "carries only %s — GSPMD would reject this at trace "
                    "time" % (spec, name, ax, sorted(mesh_axes)),
                    node=name)
        dup = {a for a in axes if axes.count(a) > 1}
        if dup:
            report.add(
                "spec-duplicate-axis", Severity.ERROR,
                "spec %s for %r uses axis(es) %s on more than one dim — "
                "a mesh axis can partition at most one dim of an array"
                % (spec, name, sorted(dup)), node=name)
        shape = tuple((shapes or {}).get(name) or ())
        if not shape:
            continue
        if len(parts) > len(shape):
            report.add(
                "spec-rank", Severity.ERROR,
                "spec %s has %d entries but %r has rank %d (shape %s)"
                % (spec, len(parts), name, len(shape), list(shape)),
                node=name)
            continue
        for dim, part in enumerate(parts):
            if part is None:
                continue
            size = 1
            for ax in (part if isinstance(part, (tuple, list)) else [part]):
                size *= mesh_axes.get(ax, 1)
            if size > 1 and shape[dim] % size:
                report.add(
                    "spec-divisibility", Severity.WARNING,
                    "dim %d of %r (%d) is not divisible by the %s "
                    "partitioning (%d shards) — every shard is padded"
                    % (dim, name, shape[dim], part, size), node=name)
    return report


def check_islands(islands: Dict[str, Dict[str, Any]], mesh=None,
                  shapes: Optional[Dict[str, Sequence[int]]] = None,
                  report: Optional[Report] = None,
                  context: str = "sharding") -> Report:
    """Cross-island audit: the same logical array declared with different
    layouts in different islands is **resharding thrash** — every
    boundary crossing lowers to an all-to-all/all-gather pair. With a
    mesh, each island's axes are also checked for existence (the
    currently-separate ``parallel/*`` islands each assume their own axis
    name; a unified layout must carry all of them or drop the island).
    """
    report = report if report is not None else Report(context=context)
    mesh_axes = set(mesh.axis_names) if mesh is not None else None
    by_name: Dict[str, List[Tuple[str, Any]]] = {}
    for island, specs in sorted(islands.items()):
        for name, spec in sorted(specs.items()):
            by_name.setdefault(name, []).append((island, spec))
            if mesh_axes is None:
                continue
            missing = [ax for ax in _spec_axes(spec) if ax not in mesh_axes]
            if missing:
                report.add(
                    "spec-axis", Severity.WARNING,
                    "island %r shards %r over axis(es) %s which the bound "
                    "mesh (%s) does not carry — its collectives silently "
                    "degrade to no-ops or fail at trace time; unify the "
                    "layout (ROADMAP item 1) or extend the mesh"
                    % (island, name, missing, ", ".join(sorted(mesh_axes))),
                    node=name, detail={"island": island,
                                       "missing_axes": missing})
    for name, entries in sorted(by_name.items()):
        layouts = {}
        for island, spec in entries:
            layouts.setdefault(str(spec), []).append(island)
        if len(layouts) < 2:
            continue
        shape = tuple((shapes or {}).get(name) or ())
        nbytes = int(np.prod(shape, dtype=np.int64)) * 4 if shape else 0
        report.add(
            "reshard-thrash", Severity.WARNING,
            "%r is bounced between layouts: %s — each boundary crossing "
            "reshards the whole array%s; pick ONE spec for it across "
            "islands" % (
                name,
                "; ".join("%s in %s" % (s, "/".join(isl))
                          for s, isl in sorted(layouts.items())),
                " (~%.3g MB moved per crossing)" % (nbytes / 1e6)
                if nbytes else ""),
            node=name,
            detail={"layouts": {s: isl for s, isl in layouts.items()},
                    "bytes": nbytes})
    return report


def check_replicated(mesh, specs: Dict[str, Any],
                     shapes: Dict[str, Sequence[int]],
                     dtypes: Optional[Dict[str, Any]] = None,
                     report: Optional[Report] = None,
                     min_bytes: int = FSDP_MIN_BYTES,
                     context: str = "sharding") -> Report:
    """Large fully-replicated parameters are FSDP opportunities: every
    device holds all N bytes where a sharded layout holds N/devices and
    all-gathers on use. Fires ``fsdp-opportunity`` (WARNING) with the
    estimated bytes recovered per device."""
    report = report if report is not None else Report(context=context)
    n_dev = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    if n_dev < 2:
        return report
    for name in sorted(shapes):
        shape = tuple(shapes[name])
        if not shape:
            continue
        if _spec_axes(specs.get(name)):
            continue                      # already partitioned
        itemsize = np.dtype((dtypes or {}).get(name, np.float32)).itemsize
        nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize
        if nbytes < min_bytes:
            continue
        recovered = nbytes * (n_dev - 1) // n_dev
        report.add(
            "fsdp-opportunity", Severity.WARNING,
            "%r (%.3g MB) is fully replicated across %d devices — "
            "sharding it (FSDP / ZeRO-style, largest dim over the data "
            "axis) recovers ~%.3g MB of HBM per device at the cost of an "
            "all-gather on use"
            % (name, nbytes / 1e6, n_dev, recovered / 1e6),
            node=name,
            detail={"bytes": nbytes, "recovered_bytes_per_device":
                    int(recovered), "devices": n_dev})
    return report


# -------------------------------------------------------- collective walk


def _axis_groups(mesh) -> Dict[frozenset, Tuple[str, ...]]:
    """Map replica-group sets -> mesh axis subsets. For every non-empty
    subset of axes, the groups are the device-id sets that vary over
    those axes with the others fixed (the groups GSPMD emits)."""
    import itertools
    names = list(mesh.axis_names)
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    out: Dict[frozenset, Tuple[str, ...]] = {}
    # descending subset size so the SMALLEST subset wins a collision —
    # on a mesh with a size-1 axis ({"data": 1, "model": 8}) the
    # ('model',) and ('data','model') groups are identical, and the
    # per-axis table must report the one users grep for ('model')
    for r in range(len(names), 0, -1):
        for combo in itertools.combinations(range(len(names)), r):
            keep = [i for i in range(len(names)) if i not in combo]
            perm = ids.transpose(keep + list(combo))
            flat = perm.reshape(-1, int(np.prod(
                [ids.shape[i] for i in combo], dtype=np.int64)))
            groups = frozenset(frozenset(int(x) for x in row)
                               for row in flat)
            out[groups] = tuple(names[i] for i in combo)
    return out


def _shape_bytes(shape_str: str, largest_only: bool = False) -> int:
    """Bytes of an HLO shape string (``f32[16,32]{1,0}`` or a tuple
    ``(f32[4], f32[4])``). ``largest_only`` takes the biggest single
    array instead of the sum — async ``*-start`` forms return an
    (operand-alias, result[, context...]) tuple where only the result
    buffer actually moves; summing would double-count."""
    sizes = []
    for dt, dims in _SHAPE_RE.findall(shape_str):
        width = re.search(r"(\d+)$", dt)
        itemsize = max(1, int(width.group(1)) // 8) if width else 4
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * itemsize)
    if not sizes:
        return 0
    return max(sizes) if largest_only else sum(sizes)


def comm_link_bytes(kind: str, nbytes: int, group_size: int) -> int:
    """Bytes crossing the busiest link for one collective over a ring of
    ``group_size`` devices moving an ``nbytes`` buffer (the standard
    ring-algorithm counts; the model behind the per-axis time
    estimates)."""
    n = max(1, int(group_size))
    if n == 1:
        return 0
    if kind == "all-reduce":
        return int(2 * nbytes * (n - 1) / n)
    if kind in ("all-gather", "reduce-scatter", "all-to-all"):
        return int(nbytes * (n - 1) / n)
    if kind == "collective-permute":
        return int(nbytes)
    return int(nbytes)


def device_table_lookup(table, override_knob: str, default=None,
                        device_kind: Optional[str] = None):
    """The shared knob-then-device-kind ladder every bandwidth/peak
    table uses: a positive config override wins, else the first
    substring match of the (probed) ``device_kind`` in ``table``, else
    ``default``. One implementation so a new TPU generation is added in
    the tables, not in N copies of the lookup."""
    from .. import config as _config
    override = float(_config.get(override_knob))
    if override > 0:
        return override
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:                                   # noqa: BLE001
            device_kind = ""
    dk = (device_kind or "").lower()
    for sub, val in table:
        if sub in dk:
            return val
    return default


def ici_gbps(device_kind: Optional[str] = None) -> float:
    return device_table_lookup(ICI_GBPS_BY_DEVICE_KIND,
                               "MXNET_TPU_ANALYZE_ICI_GBPS",
                               default=_DEFAULT_ICI_GBPS,
                               device_kind=device_kind)


def collectives_from_hlo(hlo_text: str, mesh=None) -> List[Dict[str, Any]]:
    """Parse post-partitioning HLO for collectives; one record per op
    with kind, per-shard buffer bytes, replica-group size and — when the
    groups match a mesh axis subset — the axis attribution."""
    groups_map = _axis_groups(mesh) if mesh is not None else {}
    records: List[Dict[str, Any]] = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        kind = m.group("kind")
        nbytes = _shape_bytes(m.group("shape"),
                              largest_only=bool(m.group("start")))
        gm = _GROUPS_RE.search(line)
        gm2 = _GROUPS_V2_RE.search(line)
        group_size = 1
        axes: Tuple[str, ...] = ()
        groups = None
        if gm:
            groups = frozenset(
                frozenset(int(x) for x in g.split(",") if x.strip())
                for g in re.findall(r"\{([^}]*)\}", gm.group(1)))
        elif gm2:
            # iota form [G,S]<=[dims]T(perm): device ids are
            # iota(prod(dims)).reshape(dims).transpose(perm) flattened
            # into G groups of S
            g_n, g_s = int(gm2.group(1)), int(gm2.group(2))
            dims = [int(d) for d in gm2.group(3).split(",")]
            ids = np.arange(int(np.prod(dims))).reshape(dims)
            if gm2.group(4):
                ids = ids.transpose([int(p)
                                     for p in gm2.group(4).split(",")])
            ids = ids.reshape(g_n, g_s)
            groups = frozenset(frozenset(int(x) for x in row)
                               for row in ids)
        if groups is not None:
            group_size = max((len(g) for g in groups), default=1)
            axes = groups_map.get(groups, ())
        elif kind == "collective-permute":
            pm = _PAIRS_RE.search(line)
            if pm:
                pairs = re.findall(r"\{(\d+),(\d+)\}", pm.group(0))
                group_size = len({int(a) for a, _ in pairs}) or 1
        records.append({
            "kind": kind, "bytes": nbytes, "group_size": group_size,
            "axes": list(axes),
            "link_bytes": comm_link_bytes(kind, nbytes, group_size),
        })
    return records


def analyze_collectives(fn, *args, mesh=None, in_shardings=None,
                        out_shardings=None, static_argnums=(),
                        context: str = "collectives",
                        report: Optional[Report] = None,
                        **kwargs) -> Report:
    """Compile ``fn`` against its shardings and cost its collectives.

    ``args`` may be committed (already-sharded) arrays — jit then infers
    the input layouts — or plain arrays with explicit ``in_shardings``.
    ``Report.extras["comm"]``:

    * ``collectives`` — every collective with bytes/axis/link cost;
    * ``per_axis`` — aggregated buffer bytes, link bytes and the
      ring-model time estimate per mesh axis (the number the acceptance
      test hand-checks);
    * ``total_link_bytes`` / ``est_total_us``.
    """
    import jax

    report = report if report is not None else Report(context=context)
    jit_kw: Dict[str, Any] = {"static_argnums": static_argnums}
    if in_shardings is not None:
        jit_kw["in_shardings"] = in_shardings
    if out_shardings is not None:
        jit_kw["out_shardings"] = out_shardings
    compiled = jax.jit(fn, **jit_kw).lower(*args, **kwargs).compile()
    records = collectives_from_hlo(compiled.as_text(), mesh=mesh)
    bw = ici_gbps() * 1e9
    per_axis: Dict[str, Dict[str, float]] = {}
    total_link = 0
    for rec in records:
        rec["est_us"] = rec["link_bytes"] / bw * 1e6
        key = "x".join(rec["axes"]) if rec["axes"] else "<unattributed>"
        agg = per_axis.setdefault(key, {"bytes": 0, "link_bytes": 0,
                                        "est_us": 0.0, "count": 0})
        agg["bytes"] += rec["bytes"]
        agg["link_bytes"] += rec["link_bytes"]
        agg["est_us"] += rec["est_us"]
        agg["count"] += 1
        total_link += rec["link_bytes"]
    comm = {
        "collectives": records,
        "per_axis": per_axis,
        "total_link_bytes": int(total_link),
        "est_total_us": round(total_link / bw * 1e6, 3),
        "link_gbps": bw / 1e9,
    }
    report.extras["comm"] = comm
    report.add(
        "comm-model", Severity.INFO,
        "%d collective(s), %.3g MB on the busiest links (~%.3g us at "
        "%.0f GB/s): %s"
        % (len(records), total_link / 1e6, comm["est_total_us"], bw / 1e9,
           "; ".join("%s: %d op(s) %.3g MB" % (ax, agg["count"],
                                               agg["bytes"] / 1e6)
                     for ax, agg in sorted(per_axis.items())) or "none"),
        detail={k: v for k, v in comm.items() if k != "collectives"})
    return report


# ------------------------------------------------------------ module audit


def analyze_module_sharding(mod, collectives: bool = True,
                            context: str = "module-sharding") -> Report:
    """The full sharding audit of a mesh-bound ``Module``: specs are
    resolved exactly as the bind path resolves them (regex and all), the
    program is the bound executor's forward. Returns an empty report for
    mesh-less modules (nothing to audit)."""
    import jax

    report = Report(context=context)
    mesh = getattr(mod, "_mesh", None)
    if mesh is None:
        return report
    ex = mod._exec
    shapes = {n: tuple(a.shape) for n, a in ex.arg_dict.items()}
    shapes.update({n: tuple(a.shape) for n, a in ex.aux_dict.items()})
    dtypes = {n: a.dtype for n, a in ex.arg_dict.items()}
    dtypes.update({n: a.dtype for n, a in ex.aux_dict.items()})
    specs = {}
    for name in list(ex.arg_dict) + list(ex.aux_dict):
        sharding = mod._sharding_for(name)
        specs[name] = sharding.spec
    # the FSDP audit is about PARAMETERS (and aux state) the module
    # holds resident — data/label inputs are batch-sharded per step by
    # the placer, not replicated residents, and must not be flagged
    resident = list(getattr(mod, "_param_names", shapes)) \
        + list(getattr(mod, "_aux_names", ()))
    param_specs = {n: specs[n] for n in resident if n in specs}
    param_shapes = {n: shapes[n] for n in resident if n in shapes}
    check_specs(mesh, specs, shapes, report=report)
    check_replicated(mesh, param_specs, param_shapes, dtypes,
                     report=report)
    # ambiguous regex layering: two patterns matching one param with
    # different specs is a latent reshard (first-match wins today; a
    # reorder silently changes the layout)
    if getattr(mod, "_param_shardings", None):
        pats = list(mod._param_shardings.items())
        for name in sorted(param_specs):
            # mirror _sharding_for's resolution exactly: an exact key
            # wins unconditionally (deterministic — NOT a conflict, no
            # matter what regexes also match); ambiguity exists only
            # among >1 regex matches with no exact key
            if name in mod._param_shardings:
                continue
            matches = [(p, s) for p, s in pats if re.fullmatch(p, name)]
            if len({str(s) for _, s in matches}) > 1:
                report.add(
                    "spec-conflict", Severity.WARNING,
                    "%r matches %d sharding patterns with different specs "
                    "(%s) — first match wins; make one pattern "
                    "authoritative"
                    % (name, len(matches),
                       "; ".join("%r -> %s" % m for m in matches)),
                    node=name)
    if collectives:
        fn = ex._fn
        key = jax.random.PRNGKey(0)
        args = {n: a.data for n, a in ex.arg_dict.items()}
        aux = {n: a.data for n, a in ex.aux_dict.items()}
        try:
            analyze_collectives(
                lambda a, x: fn(a, x, key, False)[0], args, aux,
                mesh=mesh, report=report, context=context)
        except Exception as exc:                            # noqa: BLE001
            report.add(
                "comm-model", Severity.INFO,
                "collective walk unavailable for this program (%s: %s)"
                % (type(exc).__name__,
                   (str(exc).splitlines() or [""])[0][:120]))
    return report

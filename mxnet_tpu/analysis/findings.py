"""Finding/Report containers shared by every analyzer layer.

One vocabulary for all three analyzers (graph passes, program passes, the
AST lint): a :class:`Finding` is one located hazard with a stable ``code``
(the hazard class), a :class:`Severity`, and a human message that names the
offending node/op/file instead of a raw traceback. A :class:`Report`
collects findings, feeds the per-class profiler counters
(``analysis_<code>``), and implements the warn/strict bind-time contract.
"""
from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional

__all__ = ["Severity", "Finding", "Report"]


class Severity(enum.IntEnum):
    """ERROR findings raise under ``MXNET_TPU_ANALYZE=strict``; WARNING
    findings log; INFO findings only appear in reports/CLI output."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name


class Finding:
    """One located hazard.

    ``code`` is the stable hazard-class slug (``cycle``, ``baked-const``,
    ``lock-host-sync``, ...) — the unit tests, the profiler counters and
    the CI baseline all key on it, so it must never encode volatile detail
    (line numbers, shapes) — those live in ``message``/``detail``.
    """

    __slots__ = ("code", "severity", "message", "node", "op", "path",
                 "line", "func", "detail")

    def __init__(self, code: str, severity: Severity, message: str,
                 node: Optional[str] = None, op: Optional[str] = None,
                 path: Optional[str] = None, line: Optional[int] = None,
                 func: Optional[str] = None,
                 detail: Optional[Dict[str, Any]] = None):
        self.code = code
        self.severity = Severity(severity)
        self.message = message
        self.node = node
        self.op = op
        self.path = path
        self.line = line
        self.func = func
        self.detail = detail or {}

    def location(self) -> str:
        if self.path is not None:
            loc = self.path if self.line is None else \
                "%s:%d" % (self.path, self.line)
            return "%s (%s)" % (loc, self.func) if self.func else loc
        if self.node is not None:
            return "%s(name=%r)" % (self.op or "node", self.node)
        return "<program>"

    def format(self) -> str:
        return "%-7s %-16s %s: %s" % (self.severity, self.code,
                                      self.location(), self.message)

    def __repr__(self):
        return "Finding(%s)" % self.format()

    def counter_name(self) -> str:
        return "analysis_" + self.code.replace("-", "_")


class Report:
    """Accumulated findings of one analysis run.

    Every ``add`` bumps the always-on profiler counter for the finding's
    class (``analysis_<code>``), so dashboards and tests can observe
    hazard rates without holding Report objects. ``extras`` carries
    non-finding artifacts (the cost-model summary).
    """

    def __init__(self, context: str = "analysis"):
        self.context = context
        self.findings: List[Finding] = []
        self.extras: Dict[str, Any] = {}

    def add(self, code: str, severity: Severity, message: str,
            **kwargs) -> Finding:
        f = Finding(code, severity, message, **kwargs)
        self.findings.append(f)
        from .. import profiler as _profiler
        _profiler.incr_counter(f.counter_name())
        return f

    def extend(self, other: "Report") -> "Report":
        self.findings.extend(other.findings)
        self.extras.update(other.extras)
        return self

    # ------------------------------------------------------------ queries
    def by_code(self, code: str) -> List[Finding]:
        return [f for f in self.findings if f.code == code]

    def at_least(self, severity: Severity) -> List[Finding]:
        return [f for f in self.findings if f.severity >= severity]

    @property
    def errors(self) -> List[Finding]:
        return self.at_least(Severity.ERROR)

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == Severity.WARNING]

    def max_severity(self) -> Optional[Severity]:
        return max((f.severity for f in self.findings), default=None)

    def __len__(self):
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    def __bool__(self):
        # a Report is always truthy (even when empty) so callers test
        # `report.findings` / `report.errors`, not the report itself
        return True

    # ---------------------------------------------------------- rendering
    def format(self, min_severity: Severity = Severity.INFO) -> str:
        lines = [f.format() for f in self.findings
                 if f.severity >= min_severity]
        if not lines:
            return "%s: no findings" % self.context
        counts = {}
        for f in self.findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        head = "%s: %s" % (self.context, ", ".join(
            "%d %s" % (counts[s], s) for s in sorted(counts, reverse=True)))
        return "\n".join([head] + lines)

    # --------------------------------------------------------- strictness
    def enforce(self, mode: str, logger=None) -> "Report":
        """Apply the ``MXNET_TPU_ANALYZE`` contract: ``warn`` logs every
        WARNING+ finding, ``strict`` additionally raises ``MXNetError``
        when any ERROR finding exists."""
        import logging
        from ..base import MXNetError
        log = logger or logging.getLogger("mxnet_tpu.analysis")
        for f in self.at_least(Severity.WARNING):
            log.warning("%s: %s", self.context, f.format())
        if mode == "strict" and self.errors:
            raise MXNetError(
                "%s: %d ERROR finding(s) under MXNET_TPU_ANALYZE=strict:\n%s"
                % (self.context, len(self.errors),
                   "\n".join(f.format() for f in self.errors)))
        return self

"""Structure-keyed compile machinery shared by the tape backward and the
fused whole-model optimizer step.

The reference MXNet pushes every parameter update through the dependency
engine as an independent per-key op (KVStore push/pull + per-index
``Updater`` — python/mxnet/optimizer.py:940), which on TPU is hundreds of
tiny XLA dispatches per training step. The same dispatch-bound regime was
already eliminated for the backward pass (autograd._compiled_backward);
this module factors the caching scheme out of autograd so both hot paths
use ONE signature discipline, and adds :class:`FusedUpdater` — the
multi-tensor-apply layer (NVIDIA Apex / PyTorch ``_foreach_`` fused
optimizers) that batches all live ``(weight, grad, state)`` triples into a
single jitted, donated update program per (optimizer, structure).

Caching contract (used by both caches):

* A **signature** must be collision-free: two different computations may
  never map to one sig. Anything that cannot be keyed safely raises
  :class:`Uncacheable` and the caller falls back to the eager path.
* Compiled runners are stored only after a successful first run.
* Failures are negative-cached with **bounded retry**: structural
  untraceability (tracer-leak/concretization errors) pins the sig to
  eager permanently, anything else (transient allocator/runtime hiccups)
  is retried a few times before giving up — a single flaky failure must
  not permanently demote a structure to per-op dispatch.
* Every hit/compile/failure increments a :mod:`profiler` counter
  (``<name>_compile`` / ``<name>_cache_hit`` / ...) so tests and tooling
  can assert "exactly one executable per step after warmup".
"""
from __future__ import annotations

import itertools
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax

from . import lockcheck as _lockcheck
from . import profiler as _profiler

__all__ = [
    "CompileCache", "Uncacheable", "op_identity", "fn_token", "static_key",
    "aval_key", "structural_failure", "FusedUpdater", "InflightWindow",
]


class InflightWindow:
    """Bounded in-flight dispatch for the async fit loop.

    jax dispatch is asynchronous: the host can race arbitrarily far ahead
    of the device, queueing batches and executions without bound. This
    window holds one completion token (the step's output arrays) per
    dispatched step; pushing past ``depth`` blocks on the OLDEST step — a
    sliding-window sync that caps in-flight work at ``depth`` steps while
    keeping the device queue full (waiting on step ``i-K`` is flow
    control, not a pipeline break: ``K`` steps stay queued behind it).

    Donation safety rides on the same ordering: the fused step donates the
    *previous* step's output buffers (params/states swap through
    ``arg_dict`` every step, so no buffer is ever donated twice), and the
    window guarantees at most ``depth+1`` generations of parameters are
    live at once.
    """

    def __init__(self, depth: int):
        self.depth = int(depth)
        self._fifo: List[Any] = []

    def push(self, token) -> None:
        if token is None or self.depth <= 0:
            return
        self._fifo.append(token)
        if len(self._fifo) > self.depth:
            _profiler.incr_counter("loop_window_wait")
            with _profiler.span("inflight_retire", "step"):
                jax.block_until_ready(self._fifo.pop(0))

    def drain(self) -> None:
        """Epoch/teardown barrier: wait out every in-flight step (so epoch
        wall-clock logs and checkpoints see completed state)."""
        if self._fifo:
            _profiler.incr_counter("loop_window_drain")
            with _profiler.span("inflight_drain", "step"):
                jax.block_until_ready(self._fifo)
            self._fifo.clear()


class Uncacheable(Exception):
    """The structure cannot use a compiled fast path; the caller must fall
    back to eager execution."""


# ------------------------------------------------------------ CompileCache

_MAX_TRANSIENT_RETRIES = 3


class CompileCache:
    """sig -> compiled runner, with LRU-ish eviction and bounded-retry
    negative caching.

    ``name`` prefixes the profiler counters: ``<name>_compile`` (a runner
    was built and stored), ``<name>_cache_hit`` (a stored runner was
    reused), ``<name>_compile_failed`` (a build attempt raised),
    ``<name>_neg_hit`` (a sig was skipped because it previously failed).
    """

    def __init__(self, name: str, max_entries: int = 128):
        self.name = name
        self.max_entries = max_entries
        self._entries: Dict[Any, Any] = {}
        # sig -> [failure_count, permanent]
        self._failures: Dict[Any, List] = {}
        self._lock = _lockcheck.Lock(name="fused.cache_lock")

    def get(self, sig):
        with self._lock:
            runner = self._entries.get(sig)
        if runner is not None:
            _profiler.incr_counter(self.name + "_cache_hit")
        return runner

    def put(self, sig, runner) -> None:
        with self._lock:
            if sig not in self._entries and \
                    len(self._entries) >= self.max_entries:
                self._entries.pop(next(iter(self._entries)))
            self._entries[sig] = runner
            # a success wipes the failure history for this structure
            self._failures.pop(sig, None)
        _profiler.incr_counter(self.name + "_compile")

    def should_skip(self, sig) -> bool:
        """True when this structure is negative-cached: permanently
        untraceable, or transiently failed too many times."""
        with self._lock:
            rec = self._failures.get(sig)
            skip = rec is not None and \
                (rec[1] or rec[0] >= _MAX_TRANSIENT_RETRIES)
        if skip:
            _profiler.incr_counter(self.name + "_neg_hit")
        return skip

    def note_success(self, sig) -> None:
        """A cached runner executed successfully: clear the transient
        failure count so isolated hiccups spread over a long run can
        never accumulate into a permanent demotion."""
        with self._lock:
            self._failures.pop(sig, None)

    def mark_failed(self, sig, permanent: bool = False) -> None:
        with self._lock:
            if sig not in self._failures and \
                    len(self._failures) >= self.max_entries:
                self._failures.pop(next(iter(self._failures)))
            rec = self._failures.setdefault(sig, [0, False])
            rec[0] += 1
            rec[1] = rec[1] or permanent
        _profiler.incr_counter(self.name + "_compile_failed")

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._failures.clear()

    def __len__(self) -> int:
        return len(self._entries)


def _is_uncacheable(exc: BaseException) -> bool:
    """True when exc is (or was caused by) Uncacheable — jax re-raises
    user exceptions from inside traces, sometimes chained."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        if isinstance(exc, Uncacheable):
            return True
        seen.add(id(exc))
        exc = exc.__cause__ or exc.__context__
    return False


def structural_failure(exc: BaseException) -> bool:
    """Classify a compile/trace failure: structural failures (the function
    genuinely cannot be traced — python control flow on tracers, host
    round-trips) are permanent; everything else is presumed transient and
    retried with a bound."""
    if isinstance(exc, Uncacheable):
        return True
    err = jax.errors
    structural = (err.ConcretizationTypeError, err.TracerArrayConversionError,
                  err.TracerBoolConversionError,
                  err.TracerIntegerConversionError,
                  err.UnexpectedTracerError)
    if isinstance(exc, structural):
        return True
    return isinstance(exc, (TypeError, NotImplementedError)) and \
        "Tracer" in str(exc)


# ------------------------------------------------------- signature scheme

_fn_tokens: Dict[int, Tuple[int, Any]] = {}
_fn_token_counter = itertools.count()


def fn_token(fn) -> int:
    """Stable, non-reusable identity token for a function object.

    ``id(fn)`` alone can alias after garbage collection; here the id is
    only trusted while a weakref confirms the same object is alive, and
    dead entries self-remove, so a recycled id gets a fresh token."""
    key = id(fn)
    ent = _fn_tokens.get(key)
    if ent is not None and ent[1]() is fn:
        return ent[0]
    token = next(_fn_token_counter)
    try:
        ref = weakref.ref(fn, lambda _r, _k=key: _fn_tokens.pop(_k, None))
    except TypeError:
        raise Uncacheable("cannot key compiled program by %r" % (fn,))
    _fn_tokens[key] = (token, ref)
    return token


def op_identity(op):
    """Cache identity of an OpDef for compiled-program signatures.

    * Registry-global ops: the canonical name IS the identity (one fn per
      name for the process lifetime).
    * ``_Function_*`` ops: every ``autograd.Function.__call__`` builds a
      fresh custom_vjp closure, so a name-keyed sig aliases two instances
      onto the first one's compiled program (silently wrong gradients —
      the Scale(2.0)/Scale(3.0) collision) and a token-keyed sig would
      recompile on every call. They are uncacheable by construction.
    * Other closure-backed ops (hybridized ``_cached_op_*`` jits, which
      ARE reused across steps): name + a non-reusable per-fn token, so two
      same-shaped blocks that happen to share a name cannot replay each
      other's programs.
    """
    from .ops.registry import OP_REGISTRY
    if OP_REGISTRY.get(op.name) is op:
        return op.name
    if op.name.startswith("_Function_"):
        raise Uncacheable("per-call Function op %s" % op.name)
    return (op.name, fn_token(op.fn))


def static_key(v):
    """Cache-key form of a static constant — must be COLLISION-FREE:
    array-likes go through the dynamic path instead (repr of a large numpy
    array truncates, which would alias two different computations onto one
    compiled closure with a stale baked-in constant), and anything else
    unhashable beyond plain list/tuple nesting raises Uncacheable."""
    if isinstance(v, (list, tuple)):
        return tuple(static_key(x) for x in v)
    try:
        hash(v)
        return v
    except TypeError:
        raise Uncacheable(str(type(v)))


def aval_key(v) -> Tuple[tuple, Any]:
    # np.dtype objects hash/compare fast; str(dtype) costs ~15us each and
    # the trainer-step signature touches 3 arrays per param per step
    return (tuple(v.shape), v.dtype)


# --------------------------------------------------------- fused updater


def _state_raw(s):
    """NDArray state tree -> raw jax-array tree (None passes through)."""
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_state_raw(x) for x in s)
    return s._data


def _state_sig(s):
    if s is None:
        return None
    if isinstance(s, tuple):
        return tuple(_state_sig(x) for x in s)
    return aval_key(s._data)


def _dealias_states(weights, states_raw):
    """Break buffer aliasing between donated inputs before a fused call.

    Two donated arguments must never share one buffer: XLA would either
    reject the donation or hand the same memory to two outputs. Aliases
    are real in this codebase — eager optimizer ``update``s may write
    ``state._data = weight.data`` (the Test optimizer does), and a
    ``set_states`` restore can intern identical leaves — so before a
    donating fused step every state leaf that IS a weight buffer (or a
    previously-seen state leaf) is replaced by a device-side copy.
    Returns the (possibly rewritten) raw state list."""
    import jax.numpy as jnp
    seen = {id(w) for w in weights}

    def visit(s):
        if s is None:
            return None
        if isinstance(s, tuple):
            return tuple(visit(x) for x in s)
        if id(s) in seen:
            _profiler.incr_counter("trainer_step_dealias_copy")
            return jnp.copy(s)
        seen.add(id(s))
        return s

    return [visit(s) for s in states_raw]


def _commit_state(old, new):
    """Write updated raw values back into the EXISTING NDArray state tree
    in place, so ``Updater.states`` keeps one object identity whether steps
    run fused or eager (save/load_states and mid-training fallback both
    keep working)."""
    if old is None:
        return None
    if isinstance(old, tuple):
        return tuple(_commit_state(o, n) for o, n in zip(old, new))
    old._data = new
    old._version += 1
    return old


class FusedUpdater:
    """Whole-model fused optimizer step over a standard ``opt.Updater``.

    ``__call__`` gathers every live ``(index, weight, grad)`` triple and
    executes ONE jitted update program built from
    ``Optimizer.update_fused``, cached by a structure key (param
    shapes/dtypes/mults + optimizer class + static hyperparams). The
    cache is per-updater so compiled runners (which close over the
    optimizer) die with the trainer instead of pinning it in a global
    table; counters still aggregate under the shared ``trainer_step``
    prefix. Per-step hyperparameters — lr, wd, rescale_grad, the clip
    threshold, and the per-param update counts — enter as dynamic
    scalars, so LR schedules and batch-size changes never recompile.
    Weight and state buffers are donated on accelerator backends, making
    the update in-place in HBM (donation is skipped on CPU where XLA
    ignores it).

    Fallback matrix (returns False -> caller runs the per-param path):
    optimizer opts out (``fused_supported = False``, e.g. SGLD's fresh
    per-step noise), statics are unhashable, or this structure is
    negative-cached after failed builds.
    """

    def __init__(self, updater, cache: Optional[CompileCache] = None):
        self.updater = updater
        self.cache = cache or CompileCache("trainer_step")

    def try_step(self, updater, items) -> bool:
        """The ONE authoritative eligibility gate shared by Trainer.step
        and Module.update: knob enabled, the caller's updater is the
        standard opt.Updater this FusedUpdater wraps (a swapped/custom
        updater falls back), and the fused call itself succeeded."""
        from . import config as _config
        from . import optimizer as _opt
        if not _config.get("MXNET_TPU_FUSED_TRAINER"):
            return False
        if type(updater) is not _opt.Updater or self.updater is not updater:
            return False
        return self(items)

    def __call__(self, items) -> bool:
        if not items:
            return True
        opt = self.updater.optimizer
        if not getattr(opt, "fused_supported", True):
            return False
        states = self.updater.states
        # lazily create states exactly like Updater.__call__ does, BEFORE
        # signing: state structure is part of the signature
        for idx, w, _g in items:
            if idx not in states:
                states[idx] = opt.create_state(idx, w)
        try:
            sig = self._signature(opt, items)
        except (Uncacheable, AttributeError, TypeError):
            # unhashable statics, or state trees with leaves the fused
            # layer doesn't model (custom optimizers) — per-param path
            return False
        if self.cache.should_skip(sig):
            return False

        counts = [
            opt._index_update_count.get(idx, opt.begin_num_update) + 1
            for idx, _w, _g in items
        ]
        t_step = max(counts)
        # replicate the eager per-param lr sequence EXACTLY: update() reads
        # the scheduler at the CURRENT num_update and only then advances it,
        # so at a schedule boundary the first param of the step still sees
        # the old lr while later params see the new one
        if opt.lr_scheduler is not None:
            base_lrs = []
            num_update = opt.num_update
            for cnt in counts:
                base_lrs.append(float(opt.lr_scheduler(num_update)))
                num_update = max(num_update, cnt)
        else:
            base_lrs = [float(opt.lr)] * len(counts)
        # python scalars throughout (lists = pytrees of scalar leaves):
        # they trace as WEAK-typed scalars, so f16 weights stay f16 under
        # `w - lr*g` exactly like the eager path's python-float hypers —
        # a strong f32 array here would silently promote every f16
        # param/state to f32 on the first fused step
        hypers = {
            "lrs": base_lrs,
            "wd": float(opt.wd),
            "rescale_grad": float(opt.rescale_grad),
            "ts": [int(c) for c in counts],
        }
        if opt._clip_active():
            # only the positive-threshold case clips; non-positive values
            # mean "disabled" (the eager ops' convention) and must not be
            # lifted to a traced always-on threshold
            hypers["clip"] = float(opt.clip_gradient)

        weights = [w._data for _i, w, _g in items]
        grads = [g._data for _i, _w, g in items]
        states_raw = [_state_raw(states[idx]) for idx, _w, _g in items]
        if jax.default_backend() != "cpu":
            # donated inputs must not share buffers (weight-aliased state
            # after an eager step or a set_states restore)
            states_raw = _dealias_states(weights, states_raw)

        # recording off around the trace: a step() issued inside
        # autograd.record() must not spill tracer-valued update ops onto
        # the global tape (lazy import: autograd imports this module)
        from . import autograd as _autograd
        donate = jax.default_backend() != "cpu"
        from .obs import compiles as _obs_compiles
        prev_rec = _autograd.set_recording(False)
        try:
            runner = self.cache.get(sig)
            if runner is None:
                runner = self._build_runner(
                    opt, [idx for idx, _w, _g in items], donate)
                call_w, call_s = weights, states_raw
                if donate:
                    # first (compiling) call runs on COPIES: if it fails,
                    # the donated copies are what got invalidated and the
                    # live weight/state buffers stay valid for the eager
                    # fallback; on success the copies' outputs replace the
                    # originals below anyway
                    import jax.numpy as jnp
                    call_w = [jnp.copy(w) for w in weights]
                    call_s = jax.tree_util.tree_map(jnp.copy, states_raw)
                try:
                    with _obs_compiles.scope("trainer_step", sig):
                        new_ws, new_ss = runner(call_w, grads, call_s,
                                                hypers)
                except Exception as e:                     # noqa: BLE001
                    self.cache.mark_failed(sig,
                                           permanent=structural_failure(e))
                    if _is_uncacheable(e):
                        # structure-independent refusal (impure update()):
                        # a per-sig negative cache can't help when the
                        # evolving attr lands in the sig itself — pin the
                        # INSTANCE so later steps skip without re-tracing
                        opt.fused_supported = False
                    return False
                self.cache.put(sig, runner)
            else:
                try:
                    new_ws, new_ss = runner(weights, grads, states_raw,
                                            hypers)
                    self.cache.note_success(sig)
                except Exception as e:                     # noqa: BLE001
                    self.cache.mark_failed(sig,
                                           permanent=structural_failure(e))
                    if donate:
                        # the live buffers were donated to the failed
                        # execution and may be invalid — there is no safe
                        # eager fallback; surface the failure loudly
                        raise
                    return False
        finally:
            _autograd.set_recording(prev_rec)

        for (idx, w, _g), nw, ns, cnt in zip(items, new_ws, new_ss, counts):
            w._data = nw
            w._version += 1
            states[idx] = _commit_state(states[idx], ns)
            opt._index_update_count[idx] = cnt
        opt.num_update = max(opt.num_update, t_step)
        return True

    def _signature(self, opt, items):
        per_param = []
        for idx, w, g in items:
            per_param.append((
                static_key(idx),
                aval_key(w._data),
                aval_key(g._data),
                _state_sig(self.updater.states[idx]),
                opt._resolve_mult(opt.lr_mult, idx),
                opt._resolve_mult(opt.wd_mult, idx),
            ))
        return (opt._fused_static_key(), tuple(per_param))

    @staticmethod
    def _build_runner(opt, indices, donate):
        def step_fn(weights, grads, states, hypers):
            return opt.update_fused(indices, weights, grads, states, hypers)

        if donate:
            # in-place HBM update; old buffers die with the rebind below.
            # (CPU XLA ignores donation and warns, so skip it there.)
            return jax.jit(step_fn, donate_argnums=(0, 2))
        return jax.jit(step_fn)

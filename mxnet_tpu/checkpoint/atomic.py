"""Atomic durable file writes — the crash-safety floor every persisted
artifact in the package sits on.

The legacy writers (``nd.save``, ``symbol.save``, ``model.save_checkpoint``,
``Predictor.export``) used to ``open(path, "wb")`` in place: a crash or
``kill -9`` mid-write leaves a torn file AT THE FINAL NAME, which later
loads half-parse into garbage or fail outright — and the previous good
checkpoint is already gone. POSIX gives an airtight protocol instead:

1. write the full payload to a temp file **in the same directory** (same
   filesystem, so the final rename cannot degrade to copy+delete),
2. ``fsync`` the temp file (data durable before it becomes visible),
3. ``os.replace`` onto the final name (atomic within a filesystem: readers
   see the old bytes or the new bytes, never a mix),
4. ``fsync`` the directory (the *rename itself* durable across power loss).

``atomic_open`` packages that protocol as a drop-in for ``open(path, mode)``.
On any exception the temp file is removed and the previous file (if any)
is untouched. stdlib-only on purpose: ``ndarray``/``symbol`` import this at
save time with zero package-import-order risk.
"""
from __future__ import annotations

import contextlib
import os
import re
import tempfile

__all__ = ["atomic_open", "fsync_dir", "replace_and_sync"]

_UMASK: int = -1


def _process_umask() -> int:
    """The process umask, read once and cached: os.umask can only be read
    by writing, and flipping it per-save would race other threads
    creating files in that window."""
    global _UMASK
    if _UMASK < 0:
        current = os.umask(0)
        os.umask(current)
        _UMASK = current
    return _UMASK


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True        # EPERM: exists but not ours


def _reap_stale(directory: str, base: str) -> None:
    """Unlink temp files for this SAME target left by writers whose pid
    is gone (kill -9 mid-write): without this, periodic saves through
    atomic_open would accumulate unbounded hidden temp files — each the
    full size of the artifact — in the user's output directory."""
    pat = re.compile(r"^\.%s\.tmp-(\d+)-" % re.escape(base))
    try:
        for name in os.listdir(directory):
            m = pat.match(name)
            if m and not _pid_alive(int(m.group(1))):
                try:
                    os.unlink(os.path.join(directory, name))
                except OSError:
                    pass
    except OSError:
        pass


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename/creation inside it survives power
    loss (no-op on platforms that refuse O_DIRECTORY opens)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass           # some filesystems reject fsync on directories
    finally:
        os.close(fd)


def replace_and_sync(tmp: str, final: str) -> None:
    """Atomically move ``tmp`` onto ``final`` and make the rename durable."""
    os.replace(tmp, final)
    fsync_dir(os.path.dirname(os.path.abspath(final)))


@contextlib.contextmanager
def atomic_open(path: str, mode: str = "wb"):
    """``open(path, mode)`` with all-or-nothing semantics.

    Yields a file object backed by a hidden temp file next to ``path``;
    on clean exit the data is fsynced and renamed over ``path``, on
    exception the temp file is deleted and ``path`` is untouched. Only
    write modes make sense here (``"wb"``/``"w"``).
    """
    if "r" in mode or "a" in mode or "+" in mode:
        raise ValueError("atomic_open is write-only, got mode %r" % mode)
    directory = os.path.dirname(os.path.abspath(path))
    base = os.path.basename(path)
    _reap_stale(directory, base)
    # pid in the name drives _reap_stale's dead-writer detection
    fd, tmp = tempfile.mkstemp(prefix=".%s.tmp-%d-" % (base, os.getpid()),
                               dir=directory)
    f = None
    try:
        # mkstemp creates 0600 and os.replace preserves it; a plain
        # open() honors the umask (typically 0644) — match that so
        # artifacts don't silently become owner-only on this path
        try:
            os.chmod(tmp, 0o666 & ~_process_umask())
        except OSError:
            pass
        f = os.fdopen(fd, mode)
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        f = None
        replace_and_sync(tmp, path)
    except BaseException:
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise

"""On-disk checkpoint format: atomic directories, verifiable arrays.

A checkpoint is ONE directory ``ckpt-<step>`` under a base directory::

    base/
      ckpt-0000000040/
        arrays.npz       every tensor, stored (uncompressed) npz
        manifest.json    per-array shape/dtype/crc32 + tensor table + meta
      ckpt-0000000080/
      .tmp-ckpt-0000000120.4711   <- a writer died here; never loadable

Atomicity protocol (CheckFreq / Check-N-Run discipline): all files are
written into a ``.tmp-*`` sibling, each fsynced, the temp directory
fsynced, then ``os.rename``d onto the final name and the base directory
fsynced. A ``ckpt-*`` directory therefore either exists with its FULL
contents durable or does not exist at all — ``kill -9`` at any byte of
the write leaves only a ``.tmp-*`` residue that readers never consider
and the next writer garbage-collects.

Verification: the manifest records a crc32 over every array's raw bytes
(plus shape/dtype and file sizes). ``read_checkpoint`` recomputes and
rejects mismatches with :class:`CheckpointCorrupt`; ``load_latest`` then
falls back to the next-newest checkpoint that verifies. The npz container
is loaded with ``allow_pickle=False`` so an untrusted checkpoint can never
execute code (same stance as the legacy ``.params`` codec).

Sharded arrays (mesh-bound modules): a jax array that is not fully
replicated is saved **per shard** — one npz entry per distinct shard with
its index window recorded in the tensor table, alongside the mesh axes and
partition spec — and reassembled into a full host array on read.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import re
import shutil
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from .. import faults as _faults
from . import atomic as _atomic

__all__ = [
    "CheckpointError", "CheckpointCorrupt", "CheckpointNotFound",
    "FORMAT_VERSION", "MANIFEST_NAME", "ARRAYS_NAME",
    "checkpoint_dir_name", "list_checkpoints", "probe_valid",
    "write_checkpoint", "read_manifest", "read_checkpoint", "load_latest",
    "collect_garbage", "resolve_layout_spec", "reshard_tensors",
]

FORMAT_VERSION = "mxnet_tpu.checkpoint/1"
MANIFEST_NAME = "manifest.json"
ARRAYS_NAME = "arrays.npz"
_DIR_RE = re.compile(r"^ckpt-(\d{10})$")
_TMP_PREFIX = ".tmp-"
# .tmp-ckpt-<step>.<pid>.<seq> — the pid group drives dead-writer reaping;
# the per-process sequence keeps two writers of the SAME step (a queued
# async save racing a SIGTERM sync save) off one tmp path
_TMP_RE = re.compile(r"^\.tmp-ckpt-\d{10}\.(\d+)\.\d+$")
_TMP_SEQ = itertools.count()

log = logging.getLogger(__name__)


class CheckpointError(MXNetError):
    """Base error of the checkpoint subsystem."""


class CheckpointCorrupt(CheckpointError):
    """A checkpoint directory failed verification (torn write by a foreign
    tool, bit rot, truncation): checksum/shape/dtype mismatch or an
    unreadable container."""


class CheckpointNotFound(CheckpointError):
    """No loadable checkpoint exists under the base directory."""


# Writer injection points for the crash-safety suite, now served by the
# general fault harness (mxnet_tpu.faults): ``MXNET_TPU_FAULTS=
# ckpt.<point>@<n>[:kind]`` fires at the n-th arrival; the PR 5 env
# ``MXNET_TPU_CKPT_TEST_CRASH=<point>@<n>`` still works (faults.py
# parses it as ``ckpt.<point>@<n>:sigkill`` — the honest `kill -9
# mid-write` with deterministic timing). Never set outside tests.
def _maybe_crash(point: str) -> None:
    if _faults.armed_or_env():
        _faults.fire("ckpt." + point, default_kind="sigkill")


def _crc32(arr: np.ndarray) -> int:
    arr = np.ascontiguousarray(arr)
    return zlib.crc32(memoryview(arr).cast("B")) & 0xFFFFFFFF


def checkpoint_dir_name(step: int) -> str:
    return "ckpt-%010d" % int(step)


# ----------------------------------------------------------- shard codec

def _is_sharded(val: Any) -> bool:
    try:
        import jax
        return isinstance(val, jax.Array) and not val.is_fully_replicated
    except Exception:                                      # noqa: BLE001
        return False


def _shard_index_meta(index, shape) -> List[Optional[List[int]]]:
    """Normalize a shard's index (tuple of slices) to json: per dim
    ``[lo, hi]``, or null for a full dimension."""
    out: List[Optional[List[int]]] = []
    for d, s in enumerate(index):
        lo = 0 if s.start is None else int(s.start)
        hi = int(shape[d]) if s.stop is None else int(s.stop)
        out.append(None if (lo == 0 and hi == int(shape[d]))
                   else [lo, hi])
    # index tuples may be shorter than the rank (trailing full dims)
    out.extend([None] * (len(shape) - len(index)))
    return out


def _decompose(name: str, val: Any, arrays: Dict[str, np.ndarray]
               ) -> Dict[str, Any]:
    """Stage one tensor into the flat array table; returns its tensor-table
    entry. Sharded jax arrays are stored one entry per distinct shard."""
    if not _is_sharded(val):
        arrays[name] = np.asarray(val)
        return {"kind": "full", "key": name}
    sharding = val.sharding
    try:
        from ..parallel.mesh import axis_sizes
        mesh = axis_sizes(sharding.mesh)
        spec = str(tuple(sharding.spec))
    except AttributeError:                   # non-NamedSharding
        mesh, spec = {}, repr(sharding)
    shards_meta = []
    seen = set()
    for shard in val.addressable_shards:
        idx_meta = _shard_index_meta(shard.index, val.shape)
        key_tuple = tuple(tuple(w) if w else None for w in idx_meta)
        if key_tuple in seen:        # replicated copy of the same window
            continue
        seen.add(key_tuple)
        key = "%s@shard%d" % (name, len(shards_meta))
        arrays[key] = np.asarray(shard.data)
        shards_meta.append({"key": key, "index": idx_meta})
    return {"kind": "sharded", "shape": [int(s) for s in val.shape],
            "dtype": str(np.dtype(val.dtype)), "mesh": mesh, "spec": spec,
            "shards": shards_meta}


def _compose(name: str, entry: Dict[str, Any],
             raw: Dict[str, np.ndarray]) -> np.ndarray:
    """Inverse of :func:`_decompose` — reassemble a full host array.

    Coverage is tracked with a boolean mask, not a naive element count:
    index windows written by exotic layouts may OVERLAP (a spec that
    replicates over one axis while sharding another records a window per
    distinct slice, and two checkpoint generations merged by hand can
    overlap partially) — overlapping writes dedup by last-writer-wins
    (each source shard is independently crc-verified upstream, so
    overlapping regions hold identical bytes), while any UNCOVERED
    element is still a hard :class:`CheckpointCorrupt`."""
    if entry["kind"] == "full":
        return raw[entry["key"]]
    shape = tuple(entry["shape"])
    out = np.empty(shape, dtype=np.dtype(entry["dtype"]))
    covered = np.zeros(shape, dtype=bool)
    for sh in entry["shards"]:
        window = tuple(slice(*w) if w else slice(None)
                       for w in sh["index"])
        piece = raw[sh["key"]]
        try:
            # exact-fit only: broadcasting a smaller (crc-valid) shard
            # into a bit-rotted window would mark it covered while
            # silently replicating rows
            if out[window].shape != piece.shape:
                raise ValueError(
                    "shard shape %s does not exactly fill window shape %s"
                    % (piece.shape, out[window].shape))
            out[window] = piece
        except (ValueError, IndexError) as exc:
            raise CheckpointCorrupt(
                "sharded tensor %r: shard %r does not fit window %s: %s"
                % (name, sh["key"], sh["index"], exc)) from None
        covered[window] = True
    if not covered.all():
        missing = int(out.size - np.count_nonzero(covered))
        raise CheckpointCorrupt(
            "sharded tensor %r: shards cover %d of %d elements"
            % (name, out.size - missing, out.size))
    return out


# ----------------------------------------------------------- resharding

# re-exported from parallel.mesh: ONE canonical name->spec resolution
# shared with Module(param_shardings=...) bind-time placement, so a
# checkpoint restored by layout can never resolve differently than the
# bind that will consume it
from ..parallel.mesh import Layout, resolve_layout_spec  # noqa: E402


def reshard_tensors(tensors: Dict[str, np.ndarray], mesh, layout: Layout
                    = None, manifest: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Lay reassembled host tensors out onto a (possibly different) mesh.

    This is the elastic half of the checkpoint contract (ROADMAP item 4):
    the manifest records each sharded array's index windows + source
    mesh/spec, :func:`_compose` already reassembles the full host value,
    and this function re-lays it out onto ANY target mesh — N-chip save
    to M-chip restore, down to 1 device and back up, dp/tp/fsdp-style or
    replicated specs. Divisibility is validated per array with the
    offending name in the error (``parallel.mesh.validate_spec``);
    arrays whose recorded source mesh differs from the target count
    ``ckpt_reshard`` (the manifest, when given, provides the recorded
    source meshes)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from .. import profiler as _profiler
    from ..parallel.mesh import axis_sizes, validate_spec
    table = (manifest or {}).get("tensors", {})
    target = axis_sizes(mesh)
    out: Dict[str, Any] = {}
    resharded = 0
    for name, arr in tensors.items():
        spec = resolve_layout_spec(layout, name)
        try:
            validate_spec(mesh, spec, np.shape(arr), name=name)
        except ValueError as exc:
            raise CheckpointError("reshard-on-load: %s" % exc) from None
        sharding = NamedSharding(mesh, spec if spec is not None
                                 else PartitionSpec())
        out[name] = jax.device_put(arr, sharding)
        src_mesh = table.get(name, {}).get("mesh")
        if src_mesh is not None and src_mesh != target:
            resharded += 1
    if resharded:
        _profiler.incr_counter("ckpt_reshard", resharded)
    return out


# ------------------------------------------------------------- writing

def write_checkpoint(base: str, step: int, tensors: Dict[str, Any],
                     meta: Optional[Dict[str, Any]] = None) -> str:
    """Write one atomic checkpoint directory; returns its path.

    ``tensors`` maps name -> array-like (numpy or jax; device arrays are
    fetched to host here — call this off the hot thread). If a VALID
    checkpoint already exists at the target directory the write is
    skipped (one state per step: two saves of the same step hold the
    same params/opt state, even if their loop meta differs — e.g. an
    epoch-end save landing on the step of the last mid-epoch save;
    resume handles a landed-on-last-batch checkpoint by falling through
    to the epoch-end processing). An existing directory that FAILS the
    validity probe (bit rot, torn by a foreign tool — the thing resume
    just fell back past) is replaced: it must not block re-checkpointing
    the retraced step forever.
    """
    step = int(step)
    os.makedirs(base, exist_ok=True)
    final = os.path.join(base, checkpoint_dir_name(step))
    if os.path.isdir(final):
        if probe_valid(final):
            return final
        log.warning("replacing invalid existing checkpoint %s", final)
        shutil.rmtree(final, ignore_errors=True)
    tmp = os.path.join(base, "%sckpt-%010d.%d.%d"
                       % (_TMP_PREFIX, step, os.getpid(), next(_TMP_SEQ)))
    os.makedirs(tmp)
    try:
        arrays: Dict[str, np.ndarray] = {}
        tensor_table = {name: _decompose(name, val, arrays)
                        for name, val in tensors.items()}
        arrays_path = os.path.join(tmp, ARRAYS_NAME)
        if _faults.armed_or_env():
            # transient-IO drill point (EIO/ENOSPC/EINTR): fires before
            # any byte lands, so the cleanup path removes only the tmp
            # dir and the manager's bounded retry re-enters cleanly
            _faults.fire("ckpt.arrays_write", path=arrays_path,
                         default_kind="eio")
        with open(arrays_path, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        _maybe_crash("after_arrays")
        manifest = {
            "format": FORMAT_VERSION,
            "step": step,
            "arrays": {k: {"shape": [int(s) for s in v.shape],
                           "dtype": str(v.dtype),
                           "crc32": _crc32(v),
                           "nbytes": int(v.nbytes)}
                       for k, v in arrays.items()},
            "tensors": tensor_table,
            "files": {ARRAYS_NAME: os.path.getsize(arrays_path)},
            "meta": meta or {},
        }
        with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        _maybe_crash("after_manifest")
        _atomic.fsync_dir(tmp)
        _maybe_crash("before_rename")
        try:
            os.rename(tmp, final)
        except OSError:
            if not os.path.isdir(final):   # a concurrent writer of the
                raise                      # same step won the rename
            shutil.rmtree(tmp, ignore_errors=True)
        _atomic.fsync_dir(base)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


# ------------------------------------------------------------- reading

def list_checkpoints(base: str) -> List[Tuple[int, str]]:
    """``[(step, path)]`` of finalized checkpoint directories, ascending
    by step. ``.tmp-*`` residues are never listed."""
    try:
        names = os.listdir(base)
    except OSError:
        return []
    out = []
    for n in names:
        m = _DIR_RE.match(n)
        if m and os.path.isdir(os.path.join(base, n)):
            out.append((int(m.group(1)), os.path.join(base, n)))
    out.sort()
    return out


def read_manifest(path: str) -> Dict[str, Any]:
    if _faults.armed_or_env():
        # bit-rot/truncation drills: corrupt the manifest ON DISK before
        # the read, so detection + fallback run against a real torn file
        _faults.fire("ckpt.read_manifest",
                     path=os.path.join(path, MANIFEST_NAME),
                     default_kind="bitflip")
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        raise CheckpointCorrupt("unreadable manifest in %s: %s"
                                % (path, exc)) from None
    if not isinstance(manifest, dict) or \
            manifest.get("format") != FORMAT_VERSION:
        raise CheckpointCorrupt(
            "%s: unknown checkpoint format %r"
            % (path, manifest.get("format") if isinstance(manifest, dict)
               else type(manifest)))
    return manifest


def probe_valid(path: str) -> bool:
    """Cheap validity probe (no checksum pass): manifest parses and the
    container files have the recorded sizes. Used by retention GC so a
    truncated checkpoint never shields a good one from the keep quota."""
    try:
        manifest = read_manifest(path)
        for fname, size in manifest.get("files", {}).items():
            if os.path.getsize(os.path.join(path, fname)) != int(size):
                return False
        return True
    except (CheckpointError, OSError, ValueError, TypeError):
        return False


def read_checkpoint(path: str, verify: bool = True, mesh=None,
                    layout: Layout = None
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load one checkpoint directory -> (tensors, manifest), verifying
    every array against its manifest record. Raises
    :class:`CheckpointCorrupt` on ANY mismatch (wrong set of arrays,
    shape/dtype drift, checksum failure, unreadable container).

    With ``mesh=`` (and an optional ``layout=`` of name -> PartitionSpec,
    exact or regex), every tensor is additionally RE-LAID-OUT onto that
    mesh after reassembly (:func:`reshard_tensors`) — the checkpoint may
    have been saved from a completely different mesh shape/spec; each
    source shard is checksum-verified before it contributes."""
    manifest = read_manifest(path)
    arrays_path = os.path.join(path, ARRAYS_NAME)
    if _faults.armed_or_env():
        _faults.fire("ckpt.read_arrays", path=arrays_path,
                     default_kind="bitflip")
    raw: Dict[str, np.ndarray] = {}
    try:
        with np.load(arrays_path, allow_pickle=False) as zf:
            names = set(zf.files)
            want = set(manifest["arrays"])
            if names != want:
                raise CheckpointCorrupt(
                    "%s: array set mismatch (missing %s, unexpected %s)"
                    % (path, sorted(want - names), sorted(names - want)))
            for key, rec in manifest["arrays"].items():
                arr = zf[key]            # zip-level CRC also checked here
                if list(arr.shape) != list(rec["shape"]) or \
                        str(arr.dtype) != rec["dtype"]:
                    raise CheckpointCorrupt(
                        "%s: %r is %s%s, manifest says %s%s"
                        % (path, key, arr.dtype, arr.shape,
                           rec["dtype"], tuple(rec["shape"])))
                if verify and _crc32(arr) != rec["crc32"]:
                    raise CheckpointCorrupt(
                        "%s: checksum mismatch on %r" % (path, key))
                raw[key] = arr
    except CheckpointError:
        raise
    except Exception as exc:                               # noqa: BLE001
        # zipfile.BadZipFile, zlib.error, OSError, ValueError: all mean
        # the container cannot be trusted
        raise CheckpointCorrupt("%s: unreadable array container: %s"
                                % (path, exc)) from None
    try:
        tensors = {name: _compose(name, entry, raw)
                   for name, entry in manifest.get("tensors", {}).items()}
    except CheckpointError:
        raise
    except Exception as exc:                               # noqa: BLE001
        # KeyError/TypeError from a bit-rotted tensor table (JSON that
        # still parses but references arrays that don't exist) must stay
        # inside the CheckpointCorrupt taxonomy or load_latest's
        # fallback chain breaks
        raise CheckpointCorrupt("%s: corrupt tensor table: %r"
                                % (path, exc)) from None
    if mesh is not None:
        tensors = reshard_tensors(tensors, mesh, layout, manifest=manifest)
    return tensors, manifest


def load_latest(base: str, verify: bool = True, mesh=None,
                layout: Layout = None
                ) -> Tuple[str, Dict[str, Any], Dict[str, Any]]:
    """Newest checkpoint that VERIFIES -> (path, tensors, manifest).

    Corrupt/torn candidates are skipped with a warning (counted
    ``ckpt_load_fallback``); raises :class:`CheckpointNotFound` when
    nothing under ``base`` loads. ``mesh=``/``layout=`` reshard-on-load
    as in :func:`read_checkpoint`."""
    from .. import profiler as _profiler
    entries = list_checkpoints(base)
    for step, path in reversed(entries):
        try:
            tensors, manifest = read_checkpoint(path, verify=verify,
                                                mesh=mesh, layout=layout)
            _profiler.incr_counter("ckpt_load_ok")
            return path, tensors, manifest
        except CheckpointCorrupt as exc:
            _profiler.incr_counter("ckpt_load_fallback")
            log.warning("skipping corrupt checkpoint %s (%s); "
                        "falling back to the previous one", path, exc)
    raise CheckpointNotFound(
        "no loadable checkpoint under %r (%d candidate(s), all invalid)"
        % (base, len(entries)))


# ---------------------------------------------------------- retention GC

def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True        # EPERM: exists but not ours


def collect_garbage(base: str, keep_last: int,
                    keep_every: Optional[int] = None) -> int:
    """Retention: keep the newest ``keep_last`` VALID checkpoints (plus
    every ``keep_every``-th step forever), delete the remaining valid
    ones, and clear ``.tmp-*`` residues of dead writers. Returns the
    number of checkpoints removed.

    Safety rails: ``keep_last <= 0`` disables deletion entirely; the
    newest valid checkpoint is never deleted; checkpoints that fail the
    validity probe are NEVER auto-deleted (they don't count toward the
    quota either — so GC can never leave only a corrupt checkpoint
    behind) but are logged for the operator."""
    from .. import profiler as _profiler
    removed = 0
    # reap tmp residues of writers that are gone (kill -9 mid-write)
    try:
        for name in os.listdir(base):
            m = _TMP_RE.match(name)
            if m and not _pid_alive(int(m.group(1))):
                shutil.rmtree(os.path.join(base, name), ignore_errors=True)
    except OSError:
        pass
    if keep_last is None or keep_last <= 0:
        return 0
    entries = list_checkpoints(base)
    valid = [(s, p) for s, p in entries if probe_valid(p)]
    invalid = [p for s, p in entries if (s, p) not in valid]
    for p in invalid:
        log.warning("retention GC: %s fails the validity probe; leaving "
                    "it for inspection (it does not count toward "
                    "keep-last)", p)
    keep = {p for _s, p in valid[-keep_last:]}
    if keep_every and keep_every > 0:
        keep |= {p for s, p in valid if s % keep_every == 0}
    if valid:
        keep.add(valid[-1][1])
    for _step, path in valid:
        if path in keep:
            continue
        shutil.rmtree(path, ignore_errors=True)
        removed += 1
    if removed:
        _profiler.incr_counter("ckpt_gc_removed", removed)
    return removed

"""On-disk checkpoint format: atomic directories, verifiable arrays.

A checkpoint is ONE directory ``ckpt-<step>`` under a base directory::

    base/
      ckpt-0000000040/
        arrays.npz       every tensor, stored (uncompressed) npz
        manifest.json    per-array shape/dtype/crc32 + tensor table + meta
      ckpt-0000000080/
      .tmp-ckpt-0000000120.4711   <- a writer died here; never loadable

Atomicity protocol (CheckFreq / Check-N-Run discipline): all files are
written into a ``.tmp-*`` sibling, each fsynced, the temp directory
fsynced, then ``os.rename``d onto the final name and the base directory
fsynced. A ``ckpt-*`` directory therefore either exists with its FULL
contents durable or does not exist at all — ``kill -9`` at any byte of
the write leaves only a ``.tmp-*`` residue that readers never consider
and the next writer garbage-collects.

Verification: the manifest records a crc32 over every array's raw bytes
(plus shape/dtype and file sizes). ``read_checkpoint`` recomputes and
rejects mismatches with :class:`CheckpointCorrupt`; ``load_latest`` then
falls back to the next-newest checkpoint that verifies. The npz container
is loaded with ``allow_pickle=False`` so an untrusted checkpoint can never
execute code (same stance as the legacy ``.params`` codec).

Sharded arrays (mesh-bound modules): a jax array that is not fully
replicated is saved **per shard** — one npz entry per distinct shard with
its index window recorded in the tensor table, alongside the mesh axes and
partition spec — and reassembled into a full host array on read.

Multi-host pods (ISSUE 11): when a ``jax.distributed`` pod is active,
the save goes **process-local** — each host writes ONLY the index
windows it owns into its own ``arrays-p<rank>.npz`` (distinct-window
ownership is derived from the global device→index map, lowest
``(process_index, device id)`` wins, so every host computes the same
partition without communicating), then publishes its shard record to
the coordination KV store AND as a fsynced ``record-p<rank>.json``
file inside the staging dir; rank 0 waits for every record (bounded by
``MXNET_TPU_CKPT_POD_TIMEOUT``), merges them into ONE manifest tagged
with ``world_size`` + per-entry ``process_index``, and commits with the
same fsync+rename protocol. A host dying mid-save means rank 0 times
out and the save aborts AS A UNIT — no partial checkpoint can ever
commit; ``load_latest`` falls back to the newest complete one. Reads
reassemble from all per-host files and reshard onto whatever world
resumes.

Leader death mid-commit (ISSUE 12): if rank 0 itself dies between
shard-record publication and the manifest commit, the KV records died
with the coordination service but the record FILES did not — a
successor leader runs :func:`finalize_staged_pod_saves` to audit each
orphaned staging dir from disk alone and deterministically finalize
(all records present + shard files at recorded sizes → commit the
merged manifest with ``meta.pod_commit`` provenance) or abort (leave
the dir for retention GC). ``load_latest`` never observes a torn
manifest on either path.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import re
import shutil
import time as _time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from .. import faults as _faults
from . import atomic as _atomic

__all__ = [
    "CheckpointError", "CheckpointCorrupt", "CheckpointNotFound",
    "CheckpointPodError",
    "FORMAT_VERSION", "MANIFEST_NAME", "ARRAYS_NAME",
    "checkpoint_dir_name", "list_checkpoints", "probe_valid",
    "write_checkpoint", "read_manifest", "read_checkpoint", "load_latest",
    "collect_garbage", "resolve_layout_spec", "reshard_tensors",
    "pod_info", "finalize_staged_pod_saves",
]

FORMAT_VERSION = "mxnet_tpu.checkpoint/1"
MANIFEST_NAME = "manifest.json"
ARRAYS_NAME = "arrays.npz"
_DIR_RE = re.compile(r"^ckpt-(\d{10})$")
_TMP_PREFIX = ".tmp-"
# .tmp-ckpt-<step>.<pid>.<seq> — the pid group drives dead-writer reaping;
# the per-process sequence keeps two writers of the SAME step (a queued
# async save racing a SIGTERM sync save) off one tmp path
_TMP_RE = re.compile(r"^\.tmp-ckpt-\d{10}\.(\d+)\.\d+$")
# .tmp-ckpt-<step>.pod.g<gen> — the shared staging dir of a pod save
# (every host writes its arrays-p<rank>.npz into it; reaped by
# collect_garbage once its step finalized, its generation is gone, or
# it aged out — a dead pod's residue has no live pid to key on)
_POD_TMP_RE = re.compile(r"^\.tmp-ckpt-(\d{10})\.pod\.g(.+)$")
_POD_TMP_MAX_AGE = 3600.0
# record-p<rank>.json — each host's fsynced shard record INSIDE the
# staging dir (its KV twin dies with the coordination service; the file
# is what a successor leader finalizes from)
_RECORD_NAME = "record-p%d.json"
_RECORD_RE = re.compile(r"^record-p(\d+)\.json$")
_TMP_SEQ = itertools.count()

log = logging.getLogger(__name__)


class CheckpointError(MXNetError):
    """Base error of the checkpoint subsystem."""


class CheckpointCorrupt(CheckpointError):
    """A checkpoint directory failed verification (torn write by a foreign
    tool, bit rot, truncation): checksum/shape/dtype mismatch or an
    unreadable container."""


class CheckpointNotFound(CheckpointError):
    """No loadable checkpoint exists under the base directory."""


class CheckpointPodError(CheckpointError):
    """A multi-host save could not complete as a unit (a peer died or
    wedged mid-save, the commit barrier timed out). The staged files are
    never renamed into place, so readers never see the partial save; the
    preemption path treats this as best-effort (the newest COMPLETE
    checkpoint is the resume point)."""


def pod_info() -> Tuple[int, int]:
    """(rank, world) of the active ``jax.distributed`` pod, (0, 1) when
    single-process. A pure state probe — never initializes anything and
    never imports ``mxnet_tpu.parallel.dist`` (the zero-cost gate
    asserts a plain single-process run stays free of the pod stack)."""
    import sys
    if "jax" not in sys.modules:
        return 0, 1
    try:
        from jax._src import distributed as _jdist
        state = _jdist.global_state
        if getattr(state, "client", None) is None:
            return 0, 1
        return int(state.process_id or 0), int(state.num_processes or 1)
    except Exception:                                      # noqa: BLE001
        return 0, 1


# Writer injection points for the crash-safety suite, now served by the
# general fault harness (mxnet_tpu.faults): ``MXNET_TPU_FAULTS=
# ckpt.<point>@<n>[:kind]`` fires at the n-th arrival; the PR 5 env
# ``MXNET_TPU_CKPT_TEST_CRASH=<point>@<n>`` still works (faults.py
# parses it as ``ckpt.<point>@<n>:sigkill`` — the honest `kill -9
# mid-write` with deterministic timing). Never set outside tests.
def _maybe_crash(point: str) -> None:
    if _faults.armed_or_env():
        _faults.fire("ckpt." + point, default_kind="sigkill")


def _blackbox():
    """The flight-recorder gate (one implementation:
    ``profiler.blackbox`` — zero-import when the knob is off). The pod
    commit phases recorded here (record published / manifest committed
    / unit abort) are what the post-mortem CLI orders against a
    mid-save death."""
    from .. import profiler as _profiler
    return _profiler.blackbox()


def _crc32(arr: np.ndarray) -> int:
    arr = np.ascontiguousarray(arr)
    return zlib.crc32(memoryview(arr).cast("B")) & 0xFFFFFFFF


def checkpoint_dir_name(step: int) -> str:
    return "ckpt-%010d" % int(step)


# ----------------------------------------------------------- shard codec

def _is_sharded(val: Any) -> bool:
    try:
        import jax
        return isinstance(val, jax.Array) and not val.is_fully_replicated
    except Exception:                                      # noqa: BLE001
        return False


def _shard_index_meta(index, shape) -> List[Optional[List[int]]]:
    """Normalize a shard's index (tuple of slices) to json: per dim
    ``[lo, hi]``, or null for a full dimension."""
    out: List[Optional[List[int]]] = []
    for d, s in enumerate(index):
        lo = 0 if s.start is None else int(s.start)
        hi = int(shape[d]) if s.stop is None else int(s.stop)
        out.append(None if (lo == 0 and hi == int(shape[d]))
                   else [lo, hi])
    # index tuples may be shorter than the rank (trailing full dims)
    out.extend([None] * (len(shape) - len(index)))
    return out


def _decompose(name: str, val: Any, arrays: Dict[str, np.ndarray]
               ) -> Dict[str, Any]:
    """Stage one tensor into the flat array table; returns its tensor-table
    entry. Sharded jax arrays are stored one entry per distinct shard."""
    if not _is_sharded(val):
        arrays[name] = np.asarray(val)
        return {"kind": "full", "key": name}
    sharding = val.sharding
    try:
        from ..parallel.mesh import axis_sizes
        mesh = axis_sizes(sharding.mesh)
        spec = str(tuple(sharding.spec))
    except AttributeError:                   # non-NamedSharding
        mesh, spec = {}, repr(sharding)
    shards_meta = []
    seen = set()
    for shard in val.addressable_shards:
        idx_meta = _shard_index_meta(shard.index, val.shape)
        key_tuple = tuple(tuple(w) if w else None for w in idx_meta)
        if key_tuple in seen:        # replicated copy of the same window
            continue
        seen.add(key_tuple)
        key = "%s@shard%d" % (name, len(shards_meta))
        arrays[key] = np.asarray(shard.data)
        shards_meta.append({"key": key, "index": idx_meta})
    return {"kind": "sharded", "shape": [int(s) for s in val.shape],
            "dtype": str(np.dtype(val.dtype)), "mesh": mesh, "spec": spec,
            "shards": shards_meta}


def _decompose_local(name: str, val: Any, arrays: Dict[str, np.ndarray],
                     rank: int) -> Optional[Dict[str, Any]]:
    """Pod variant of :func:`_decompose`: stage only what THIS process
    owns; returns a partial tensor-table entry (or None when nothing of
    this tensor lives here).

    Ownership of a distinct index window is the lowest
    ``(process_index, device id)`` among the devices holding it — derived
    from the global device→index map, so every host computes the same
    disjoint partition without communicating. Fully-replicated (and
    plain host) tensors are owned by rank 0."""
    if not _is_sharded(val):
        if rank != 0:
            return None
        arrays[name] = np.asarray(val)
        return {"kind": "full", "key": name, "process_index": 0}
    sharding = val.sharding
    try:
        from ..parallel.mesh import axis_sizes
        mesh = axis_sizes(sharding.mesh)
        spec = str(tuple(sharding.spec))
    except AttributeError:                   # non-NamedSharding
        mesh, spec = {}, repr(sharding)
    owners: Dict[Any, Tuple[int, int]] = {}
    pairs = None
    try:
        pairs = [(dev, idx) for dev, idx
                 in sharding.devices_indices_map(val.shape).items()]
    except Exception:                                      # noqa: BLE001
        try:                 # exotic sharding: the global shard view
            pairs = [(sh.device, sh.index) for sh in val.global_shards]
        except Exception:                                  # noqa: BLE001
            # no global window map at all: every host stages its own
            # distinct local windows. Windows REPLICATED across hosts
            # get one copy per host (the read-side coverage mask dedups
            # them), trading bytes for coverage — losing a window
            # entirely would corrupt the save
            pairs = None
    if pairs is not None:
        for dev, idx in pairs:
            meta = _shard_index_meta(idx, val.shape)
            key = tuple(tuple(w) if w else None for w in meta)
            cand = (int(dev.process_index), int(dev.id))
            cur = owners.get(key)
            if cur is None or cand < cur:
                owners[key] = cand
    shards_meta = []
    seen = set()
    for shard in val.addressable_shards:
        idx_meta = _shard_index_meta(shard.index, val.shape)
        key_t = tuple(tuple(w) if w else None for w in idx_meta)
        if key_t in seen:            # replicated copy of the same window
            continue
        owner = owners.get(key_t)
        if owner is not None and owner[0] != rank:
            continue                 # a replica some other host owns
        seen.add(key_t)
        akey = "%s@p%d.s%d" % (name, rank, len(shards_meta))
        arrays[akey] = np.asarray(shard.data)
        shards_meta.append({"key": akey, "index": idx_meta,
                            "process_index": rank})
    if not shards_meta:
        return None
    return {"kind": "sharded", "shape": [int(s) for s in val.shape],
            "dtype": str(np.dtype(val.dtype)), "mesh": mesh, "spec": spec,
            "shards": shards_meta}


def _merge_pod_records(step: int, records: Dict[int, Dict[str, Any]],
                       meta: Optional[Dict[str, Any]], world: int
                       ) -> Dict[str, Any]:
    """Rank 0's manifest merge: one manifest over every host's shard
    record. A record whose (process_index, world_size) tags disagree
    with this commit is a stale host writing into the wrong generation —
    rejected here so it can never reach disk."""
    arrays: Dict[str, Any] = {}
    tensors: Dict[str, Any] = {}
    files: Dict[str, int] = {}
    writers: Dict[str, str] = {}
    for r in sorted(records):
        rec = records[r]
        if int(rec.get("process_index", r)) != r or \
                int(rec.get("world_size", world)) != world:
            raise CheckpointPodError(
                "step %d: shard record of process %d is tagged "
                "process %s / world %s but this commit is world %d — "
                "stale host; aborting the save"
                % (step, r, rec.get("process_index"),
                   rec.get("world_size"), world))
        files[rec["file"]] = int(rec["size"])
        writers[str(r)] = rec["file"]
        for key, arec in rec["arrays"].items():
            if key in arrays:
                raise CheckpointPodError(
                    "step %d: duplicate array key %r from process %d"
                    % (step, key, r))
            arec = dict(arec)
            arec["file"] = rec["file"]
            arec["process_index"] = r
            arrays[key] = arec
        for name, entry in rec["tensors"].items():
            if entry["kind"] == "full":
                tensors[name] = entry
            elif name not in tensors:
                tensors[name] = dict(entry, shards=list(entry["shards"]))
            else:
                tensors[name]["shards"].extend(entry["shards"])
    return {
        "format": FORMAT_VERSION,
        "step": step,
        "world_size": world,
        "writers": writers,
        "arrays": arrays,
        "tensors": tensors,
        "files": files,
        "meta": meta or {},
    }


def _write_checkpoint_pod(base: str, step: int, tensors: Dict[str, Any],
                          meta: Optional[Dict[str, Any]], rank: int,
                          world: int) -> str:
    """Process-local save: every host writes only its own index windows;
    rank 0 merges the records and commits the manifest (see module
    docstring). Checkpoint write cost per host therefore stops scaling
    with pod size."""
    from ..parallel import dist as _dist
    from .. import config as _config
    timeout = float(_config.get("MXNET_TPU_CKPT_POD_TIMEOUT"))
    step = int(step)
    os.makedirs(base, exist_ok=True)
    final = os.path.join(base, checkpoint_dir_name(step))
    if os.path.isdir(final) and probe_valid(final):
        return final     # shared fs: every rank reaches the same answer
    gen = os.environ.get("MXNET_TPU_POD_GEN", "0")
    kv_ns = "mxnet_ckpt/g%s/s%010d" % (gen, step)
    tmp = os.path.join(base, "%sckpt-%010d.pod.g%s"
                       % (_TMP_PREFIX, step, gen))
    if rank == 0 and os.path.isdir(final):
        log.warning("replacing invalid existing checkpoint %s", final)
        shutil.rmtree(final, ignore_errors=True)
    os.makedirs(tmp, exist_ok=True)
    try:
        arrays: Dict[str, np.ndarray] = {}
        table: Dict[str, Any] = {}
        for name, val in tensors.items():
            entry = _decompose_local(name, val, arrays, rank)
            if entry is not None:
                table[name] = entry
        fname = "arrays-p%d.npz" % rank
        arrays_path = os.path.join(tmp, fname)
        if _faults.armed_or_env():
            _faults.fire("ckpt.arrays_write", path=arrays_path,
                         default_kind="eio")
        with open(arrays_path, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        _maybe_crash("after_arrays")
        record = {
            "file": fname, "process_index": rank, "world_size": world,
            "size": os.path.getsize(arrays_path),
            "arrays": {k: {"shape": [int(s) for s in v.shape],
                           "dtype": str(v.dtype),
                           "crc32": _crc32(v),
                           "nbytes": int(v.nbytes)}
                       for k, v in arrays.items()},
            "tensors": table,
        }
        # the shard record is ALSO a file in the staging dir (fsynced,
        # with this rank's view of the manifest meta): coordination-KV
        # entries die with the coordination service, so a SUCCESSOR
        # leader — one whose original rank 0 died between record
        # publication and manifest commit — can still deterministically
        # audit + finalize (or abort) the save from disk alone
        # (:func:`finalize_staged_pod_saves`)
        rec_path = os.path.join(tmp, _RECORD_NAME % rank)
        with open(rec_path, "w") as f:
            json.dump(dict(record, meta=meta or {}), f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        _dist.kv_set("%s/p%d" % (kv_ns, rank), json.dumps(record))
        _bb = _blackbox()
        if _bb is not None:
            # BEFORE the after_record crash point: a leader killed
            # there must carry "my record published" as its last
            # checkpoint event — the exact fact the successor-finalize
            # audit turns on
            _bb.record("ckpt", "record-published", step=step, gen=gen,
                       rank=rank)
            _bb.flush("ckpt-record")
        # the acceptance ordering drill: the leader dies AFTER its shard
        # record (file + KV) is published but BEFORE the manifest commit
        _maybe_crash("after_record")
        if rank != 0:
            # rank-0 manifest commit barrier: the save only "happened"
            # once rank 0 committed; a bounded wait so a dead rank 0
            # surfaces as an error, never a hang. The window is TWICE
            # rank 0's collection window: rank 0 may legitimately spend
            # the full timeout waiting for the slowest peer's record and
            # then still needs to audit/write/fsync/rename — a peer
            # giving up on the same clock as the collector would declare
            # a checkpoint failed that rank 0 goes on to commit
            commit = _dist.kv_get("%s/commit" % kv_ns,
                                  int(timeout * 2 * 1000))
            if commit is None:
                raise CheckpointPodError(
                    "rank 0 never committed checkpoint step %d within "
                    "%.0fs — the pod save aborted as a unit" % (step,
                                                                timeout))
            return final
        records = {0: record}
        deadline = _time.monotonic() + timeout
        for r in range(1, world):
            left_ms = max(1, int((deadline - _time.monotonic()) * 1000))
            raw = _dist.kv_get("%s/p%d" % (kv_ns, r), left_ms)
            if raw is None:
                raise CheckpointPodError(
                    "process %d of %d never published its shard record "
                    "for step %d within %.0fs — a host died or wedged "
                    "mid-save; aborting the save as a unit (no partial "
                    "checkpoint can commit)" % (r, world, step, timeout))
            records[r] = json.loads(raw)
        # pre-commit staging audit: every record's file must exist on
        # disk at its recorded size. Peers are blocked on the commit key
        # and do NOT rewrite on a rank-0 retry, so their KV records can
        # outlive their files (e.g. a foreign cleanup) — committing a
        # manifest that references a missing file would be a "successful"
        # save that can never load
        for r in sorted(records):
            fpath = os.path.join(tmp, records[r]["file"])
            try:
                size = os.path.getsize(fpath)
            except OSError:
                raise CheckpointPodError(
                    "process %d's shard file %s vanished from the "
                    "staging dir before the step-%d commit; aborting "
                    "the save as a unit"
                    % (r, records[r]["file"], step)) from None
            if size != int(records[r]["size"]):
                raise CheckpointPodError(
                    "process %d's shard file %s is %d bytes on disk "
                    "but its record says %d; aborting the step-%d save "
                    "as a unit" % (r, records[r]["file"], size,
                                   int(records[r]["size"]), step))
        manifest = _merge_pod_records(step, records, meta, world)
        # commit provenance: who landed the manifest, and on which path
        # (a successor-finalized save records the successor's rank here)
        manifest.setdefault("meta", {})["pod_commit"] = {
            "committed_by": 0, "path": "writer", "gen": gen}
        with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        _maybe_crash("after_manifest")
        _atomic.fsync_dir(tmp)
        _maybe_crash("before_rename")
        try:
            os.rename(tmp, final)
        except OSError:
            if not os.path.isdir(final):
                raise
            shutil.rmtree(tmp, ignore_errors=True)
        _atomic.fsync_dir(base)
        _dist.kv_set("%s/commit" % kv_ns, final)
        _bb = _blackbox()
        if _bb is not None:
            _bb.record("ckpt", "pod-manifest-commit", step=step,
                       gen=gen, world=world)
        return final
    except CheckpointPodError as exc:
        _bb = _blackbox()
        if _bb is not None:
            _bb.record("ckpt", "pod-abort", step=step, gen=gen,
                       error=str(exc)[:500])
            _bb.flush("ckpt-pod-abort")
        raise
    except BaseException:
        # do NOT rmtree the shared staging dir — peers' shard files live
        # in it, and a transient-error retry on this rank re-enters the
        # SAME dir while peers stay blocked on the commit key (they never
        # rewrite); deleting their files here would let the retry commit
        # a manifest referencing vanished files. The dir is never
        # renamed, so readers never see it; collect_garbage reaps it
        # (finalized step / stale generation / age).
        raise


def _compose(name: str, entry: Dict[str, Any],
             raw: Dict[str, np.ndarray]) -> np.ndarray:
    """Inverse of :func:`_decompose` — reassemble a full host array.

    Coverage is tracked with a boolean mask, not a naive element count:
    index windows written by exotic layouts may OVERLAP (a spec that
    replicates over one axis while sharding another records a window per
    distinct slice, and two checkpoint generations merged by hand can
    overlap partially) — overlapping writes dedup by last-writer-wins
    (each source shard is independently crc-verified upstream, so
    overlapping regions hold identical bytes), while any UNCOVERED
    element is still a hard :class:`CheckpointCorrupt`."""
    if entry["kind"] == "full":
        return raw[entry["key"]]
    shape = tuple(entry["shape"])
    out = np.empty(shape, dtype=np.dtype(entry["dtype"]))
    covered = np.zeros(shape, dtype=bool)
    for sh in entry["shards"]:
        window = tuple(slice(*w) if w else slice(None)
                       for w in sh["index"])
        piece = raw[sh["key"]]
        try:
            # exact-fit only: broadcasting a smaller (crc-valid) shard
            # into a bit-rotted window would mark it covered while
            # silently replicating rows
            if out[window].shape != piece.shape:
                raise ValueError(
                    "shard shape %s does not exactly fill window shape %s"
                    % (piece.shape, out[window].shape))
            out[window] = piece
        except (ValueError, IndexError) as exc:
            raise CheckpointCorrupt(
                "sharded tensor %r: shard %r does not fit window %s: %s"
                % (name, sh["key"], sh["index"], exc)) from None
        covered[window] = True
    if not covered.all():
        missing = int(out.size - np.count_nonzero(covered))
        raise CheckpointCorrupt(
            "sharded tensor %r: shards cover %d of %d elements"
            % (name, out.size - missing, out.size))
    return out


# ----------------------------------------------------------- resharding

# re-exported from parallel.mesh: ONE canonical name->spec resolution
# shared with Module(param_shardings=...) bind-time placement, so a
# checkpoint restored by layout can never resolve differently than the
# bind that will consume it
from ..parallel.mesh import Layout, resolve_layout_spec  # noqa: E402


def reshard_tensors(tensors: Dict[str, np.ndarray], mesh, layout: Layout
                    = None, manifest: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Lay reassembled host tensors out onto a (possibly different) mesh.

    This is the elastic half of the checkpoint contract (ROADMAP item 4):
    the manifest records each sharded array's index windows + source
    mesh/spec, :func:`_compose` already reassembles the full host value,
    and this function re-lays it out onto ANY target mesh — N-chip save
    to M-chip restore, down to 1 device and back up, dp/tp/fsdp-style or
    replicated specs. Divisibility is validated per array with the
    offending name in the error (``parallel.mesh.validate_spec``);
    arrays whose recorded source mesh differs from the target count
    ``ckpt_reshard`` (the manifest, when given, provides the recorded
    source meshes)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from .. import profiler as _profiler
    from ..parallel.mesh import axis_sizes, validate_spec
    table = (manifest or {}).get("tensors", {})
    target = axis_sizes(mesh)
    out: Dict[str, Any] = {}
    resharded = 0
    for name, arr in tensors.items():
        # shape-aware resolution: a SpecLayout's heuristic needs the
        # array shape (and strips the arg:/aux:/opt: key prefix itself)
        spec = resolve_layout_spec(layout, name, shape=np.shape(arr),
                                   dtype=getattr(arr, "dtype", None))
        try:
            validate_spec(mesh, spec, np.shape(arr), name=name)
        except ValueError as exc:
            raise CheckpointError("reshard-on-load: %s" % exc) from None
        sharding = NamedSharding(mesh, spec if spec is not None
                                 else PartitionSpec())
        out[name] = jax.device_put(arr, sharding)
        src_mesh = table.get(name, {}).get("mesh")
        if src_mesh is not None and src_mesh != target:
            resharded += 1
    if resharded:
        _profiler.incr_counter("ckpt_reshard", resharded)
    return out


# ------------------------------------------------------------- writing

def write_checkpoint(base: str, step: int, tensors: Dict[str, Any],
                     meta: Optional[Dict[str, Any]] = None) -> str:
    """Write one atomic checkpoint directory; returns its path.

    ``tensors`` maps name -> array-like (numpy or jax; device arrays are
    fetched to host here — call this off the hot thread). If a VALID
    checkpoint already exists at the target directory the write is
    skipped (one state per step: two saves of the same step hold the
    same params/opt state, even if their loop meta differs — e.g. an
    epoch-end save landing on the step of the last mid-epoch save;
    resume handles a landed-on-last-batch checkpoint by falling through
    to the epoch-end processing). An existing directory that FAILS the
    validity probe (bit rot, torn by a foreign tool — the thing resume
    just fell back past) is replaced: it must not block re-checkpointing
    the retraced step forever.

    Under an active ``jax.distributed`` pod this call is COLLECTIVE:
    every process must make it with the same step, each writes only its
    own index windows, and rank 0 commits the merged manifest
    (:func:`_write_checkpoint_pod`).
    """
    rank, world = pod_info()
    if world > 1:
        return _write_checkpoint_pod(base, step, tensors, meta, rank,
                                     world)
    step = int(step)
    os.makedirs(base, exist_ok=True)
    final = os.path.join(base, checkpoint_dir_name(step))
    if os.path.isdir(final):
        if probe_valid(final):
            return final
        log.warning("replacing invalid existing checkpoint %s", final)
        shutil.rmtree(final, ignore_errors=True)
    tmp = os.path.join(base, "%sckpt-%010d.%d.%d"
                       % (_TMP_PREFIX, step, os.getpid(), next(_TMP_SEQ)))
    os.makedirs(tmp)
    try:
        arrays: Dict[str, np.ndarray] = {}
        tensor_table = {name: _decompose(name, val, arrays)
                        for name, val in tensors.items()}
        arrays_path = os.path.join(tmp, ARRAYS_NAME)
        if _faults.armed_or_env():
            # transient-IO drill point (EIO/ENOSPC/EINTR): fires before
            # any byte lands, so the cleanup path removes only the tmp
            # dir and the manager's bounded retry re-enters cleanly
            _faults.fire("ckpt.arrays_write", path=arrays_path,
                         default_kind="eio")
        with open(arrays_path, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        _maybe_crash("after_arrays")
        manifest = {
            "format": FORMAT_VERSION,
            "step": step,
            "arrays": {k: {"shape": [int(s) for s in v.shape],
                           "dtype": str(v.dtype),
                           "crc32": _crc32(v),
                           "nbytes": int(v.nbytes)}
                       for k, v in arrays.items()},
            "tensors": tensor_table,
            "files": {ARRAYS_NAME: os.path.getsize(arrays_path)},
            "meta": meta or {},
        }
        with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        _maybe_crash("after_manifest")
        _atomic.fsync_dir(tmp)
        _maybe_crash("before_rename")
        try:
            os.rename(tmp, final)
        except OSError:
            if not os.path.isdir(final):   # a concurrent writer of the
                raise                      # same step won the rename
            shutil.rmtree(tmp, ignore_errors=True)
        _atomic.fsync_dir(base)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


# ------------------------------------------------------------- reading

def list_checkpoints(base: str) -> List[Tuple[int, str]]:
    """``[(step, path)]`` of finalized checkpoint directories, ascending
    by step. ``.tmp-*`` residues are never listed."""
    try:
        names = os.listdir(base)
    except OSError:
        return []
    out = []
    for n in names:
        m = _DIR_RE.match(n)
        if m and os.path.isdir(os.path.join(base, n)):
            out.append((int(m.group(1)), os.path.join(base, n)))
    out.sort()
    return out


def read_manifest(path: str) -> Dict[str, Any]:
    if _faults.armed_or_env():
        # bit-rot/truncation drills: corrupt the manifest ON DISK before
        # the read, so detection + fallback run against a real torn file
        _faults.fire("ckpt.read_manifest",
                     path=os.path.join(path, MANIFEST_NAME),
                     default_kind="bitflip")
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        raise CheckpointCorrupt("unreadable manifest in %s: %s"
                                % (path, exc)) from None
    if not isinstance(manifest, dict) or \
            manifest.get("format") != FORMAT_VERSION:
        raise CheckpointCorrupt(
            "%s: unknown checkpoint format %r"
            % (path, manifest.get("format") if isinstance(manifest, dict)
               else type(manifest)))
    return manifest


def _validate_pod_tags(path: str, manifest: Dict[str, Any]) -> None:
    """Reject a mixed-world save LEGIBLY: every ``process_index`` tag in
    the manifest (writers map, array records, shard entries) must be
    consistent with the committed ``world_size``. A violation means a
    stale host — one still writing with an old generation's world view —
    contaminated the directory; the error names it so the operator knows
    which host to hunt, and ``load_latest`` falls back to the previous
    complete checkpoint instead of failing crc-by-crc."""
    world = int(manifest.get("world_size", 1) or 1)
    for r_s, fname in (manifest.get("writers") or {}).items():
        if int(r_s) >= world:
            raise CheckpointCorrupt(
                "%s: %s was written by process %s, but the manifest "
                "commits world_size=%d — stale host file from a larger "
                "world; rejecting the save as a unit" % (path, fname,
                                                         r_s, world))
    for key, rec in (manifest.get("arrays") or {}).items():
        p = rec.get("process_index")
        if p is not None and int(p) >= world:
            raise CheckpointCorrupt(
                "%s: array %r (file %s) is tagged process %d of a "
                "world-%d-or-larger save, but the manifest commits "
                "world_size=%d — stale host; rejecting the save as a "
                "unit" % (path, key, rec.get("file", ARRAYS_NAME),
                          int(p), int(p) + 1, world))
    for name, entry in (manifest.get("tensors") or {}).items():
        for sh in entry.get("shards") or []:
            p = sh.get("process_index")
            if p is not None and int(p) >= world:
                raise CheckpointCorrupt(
                    "%s: tensor %r shard %r is tagged process %d but "
                    "the manifest commits world_size=%d — stale host"
                    % (path, name, sh.get("key"), int(p), world))


def probe_valid(path: str) -> bool:
    """Cheap validity probe (no checksum pass): manifest parses and the
    container files have the recorded sizes. Used by retention GC so a
    truncated checkpoint never shields a good one from the keep quota."""
    try:
        manifest = read_manifest(path)
        for fname, size in manifest.get("files", {}).items():
            if os.path.getsize(os.path.join(path, fname)) != int(size):
                return False
        return True
    except (CheckpointError, OSError, ValueError, TypeError):
        return False


def read_checkpoint(path: str, verify: bool = True, mesh=None,
                    layout: Layout = None
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load one checkpoint directory -> (tensors, manifest), verifying
    every array against its manifest record. Raises
    :class:`CheckpointCorrupt` on ANY mismatch (wrong set of arrays,
    shape/dtype drift, checksum failure, unreadable container).

    With ``mesh=`` (and an optional ``layout=`` of name -> PartitionSpec,
    exact or regex), every tensor is additionally RE-LAID-OUT onto that
    mesh after reassembly (:func:`reshard_tensors`) — the checkpoint may
    have been saved from a completely different mesh shape/spec; each
    source shard is checksum-verified before it contributes.

    Pod checkpoints (several ``arrays-p<rank>.npz`` containers) are
    reassembled from every per-host file; a manifest whose
    ``process_index`` tags exceed its committed ``world_size`` is a
    mixed-world partial save (a stale host wrote into the directory) and
    is rejected as a unit, NAMING the stale writer — never a
    checksum-by-checksum failure hunt."""
    manifest = read_manifest(path)
    _validate_pod_tags(path, manifest)
    by_file: Dict[str, Dict[str, Any]] = {}
    for key, rec in manifest["arrays"].items():
        by_file.setdefault(rec.get("file", ARRAYS_NAME), {})[key] = rec
    fire_path = os.path.join(
        path, ARRAYS_NAME if ARRAYS_NAME in by_file or not by_file
        else sorted(by_file)[0])
    if _faults.armed_or_env():
        _faults.fire("ckpt.read_arrays", path=fire_path,
                     default_kind="bitflip")
    raw: Dict[str, np.ndarray] = {}
    try:
        for fname in sorted(by_file):
            want_recs = by_file[fname]
            with np.load(os.path.join(path, fname),
                         allow_pickle=False) as zf:
                names = set(zf.files)
                want = set(want_recs)
                if names != want:
                    raise CheckpointCorrupt(
                        "%s: array set mismatch in %s (missing %s, "
                        "unexpected %s)"
                        % (path, fname, sorted(want - names),
                           sorted(names - want)))
                for key, rec in want_recs.items():
                    arr = zf[key]    # zip-level CRC also checked here
                    if list(arr.shape) != list(rec["shape"]) or \
                            str(arr.dtype) != rec["dtype"]:
                        raise CheckpointCorrupt(
                            "%s: %r is %s%s, manifest says %s%s"
                            % (path, key, arr.dtype, arr.shape,
                               rec["dtype"], tuple(rec["shape"])))
                    if verify and _crc32(arr) != rec["crc32"]:
                        raise CheckpointCorrupt(
                            "%s: checksum mismatch on %r" % (path, key))
                    raw[key] = arr
    except CheckpointError:
        raise
    except Exception as exc:                               # noqa: BLE001
        # zipfile.BadZipFile, zlib.error, OSError, ValueError: all mean
        # the container cannot be trusted
        raise CheckpointCorrupt("%s: unreadable array container: %s"
                                % (path, exc)) from None
    try:
        tensors = {name: _compose(name, entry, raw)
                   for name, entry in manifest.get("tensors", {}).items()}
    except CheckpointError:
        raise
    except Exception as exc:                               # noqa: BLE001
        # KeyError/TypeError from a bit-rotted tensor table (JSON that
        # still parses but references arrays that don't exist) must stay
        # inside the CheckpointCorrupt taxonomy or load_latest's
        # fallback chain breaks
        raise CheckpointCorrupt("%s: corrupt tensor table: %r"
                                % (path, exc)) from None
    if mesh is not None:
        tensors = reshard_tensors(tensors, mesh, layout, manifest=manifest)
    return tensors, manifest


def load_latest(base: str, verify: bool = True, mesh=None,
                layout: Layout = None
                ) -> Tuple[str, Dict[str, Any], Dict[str, Any]]:
    """Newest checkpoint that VERIFIES -> (path, tensors, manifest).

    Corrupt/torn candidates are skipped with a warning (counted
    ``ckpt_load_fallback``); raises :class:`CheckpointNotFound` when
    nothing under ``base`` loads. ``mesh=``/``layout=`` reshard-on-load
    as in :func:`read_checkpoint`."""
    from .. import profiler as _profiler
    entries = list_checkpoints(base)
    for step, path in reversed(entries):
        try:
            tensors, manifest = read_checkpoint(path, verify=verify,
                                                mesh=mesh, layout=layout)
            _profiler.incr_counter("ckpt_load_ok")
            return path, tensors, manifest
        except CheckpointCorrupt as exc:
            _profiler.incr_counter("ckpt_load_fallback")
            log.warning("skipping corrupt checkpoint %s (%s); "
                        "falling back to the previous one", path, exc)
    raise CheckpointNotFound(
        "no loadable checkpoint under %r (%d candidate(s), all invalid)"
        % (base, len(entries)))


# -------------------------------------------- successor finalize / abort

def finalize_staged_pod_saves(base: str, by_rank: int = 0) -> List[str]:
    """Successor-leader audit of orphaned pod staging dirs (ISSUE 12).

    A pod save whose ORIGINAL rank 0 died between shard-record
    publication and manifest commit leaves a ``.tmp-*.pod.g*`` staging
    dir holding every host's ``arrays-p<rank>.npz`` plus its fsynced
    ``record-p<rank>.json`` — everything the commit needed except the
    commit itself. This function lets the next generation's leader
    deterministically FINALIZE or ABORT each such dir:

    * every rank's record file present (the full ``world_size`` set,
      consistently tagged) AND every recorded shard file on disk at its
      recorded size → merge the records into the manifest rank 0 would
      have written (rank 0's record carries the meta), commit it with
      the same fsync→rename protocol, tagged
      ``meta.pod_commit = {path: "successor", committed_by: <rank>}``;
      counted ``ckpt_pod_finalized``;
    * anything missing or inconsistent → LEAVE the dir for retention GC
      (age / stale generation). Readers never saw it; nothing is torn.

    Staging dirs of the CURRENT generation (``MXNET_TPU_POD_GEN``) are
    never touched — they may be a live save in flight. Concurrent
    finalizers (every host resumes through :func:`~mxnet_tpu.elastic.
    resume_dir`) are safe: both build identical manifests and the
    rename is atomic — the loser observes the final dir and stands
    down. Returns the list of finalized checkpoint paths."""
    from .. import profiler as _profiler
    finalized: List[str] = []
    cur_gen = os.environ.get("MXNET_TPU_POD_GEN")
    try:
        names = os.listdir(base)
    except OSError:
        return finalized
    for name in sorted(names):
        m = _POD_TMP_RE.match(name)
        if m is None:
            continue
        step, gen = int(m.group(1)), m.group(2)
        if cur_gen is not None and gen == cur_gen:
            continue                    # possibly a live save in flight
        tmp = os.path.join(base, name)
        final = os.path.join(base, checkpoint_dir_name(step))
        if os.path.isdir(final):
            continue                    # committed; GC reaps the residue
        try:
            records: Dict[int, Dict[str, Any]] = {}
            for fn in os.listdir(tmp):
                rm = _RECORD_RE.match(fn)
                if rm is None:
                    continue
                with open(os.path.join(tmp, fn)) as f:
                    records[int(rm.group(1))] = json.load(f)
            if not records:
                continue                # pre-record death: nothing to audit
            worlds = {int(r.get("world_size", 0)) for r in records.values()}
            if len(worlds) != 1:
                log.warning("pod finalize: %s holds records of mixed "
                            "worlds %s; leaving it for GC", tmp,
                            sorted(worlds))
                continue
            world = worlds.pop()
            if set(records) != set(range(world)):
                log.warning("pod finalize: %s holds records for ranks "
                            "%s of world %d — a host died before "
                            "publishing; leaving the aborted save for "
                            "GC", tmp, sorted(records), world)
                continue
            complete = True
            for r, rec in sorted(records.items()):
                fpath = os.path.join(tmp, rec["file"])
                try:
                    size = os.path.getsize(fpath)
                except OSError:
                    size = -1
                if size != int(rec["size"]):
                    log.warning("pod finalize: %s: rank %d's shard file "
                                "%s is %d bytes, record says %s; leaving "
                                "the save for GC", tmp, r, rec["file"],
                                size, rec["size"])
                    complete = False
                    break
            if not complete:
                continue
            meta = records[0].get("meta") or {}
            manifest = _merge_pod_records(step, records, meta, world)
            manifest.setdefault("meta", {})["pod_commit"] = {
                "committed_by": int(by_rank), "path": "successor",
                "gen": gen}
            # manifest lands under a unique name first so a concurrent
            # finalizer can never interleave a half-written manifest
            part = os.path.join(tmp, "%s.%d" % (MANIFEST_NAME,
                                                os.getpid()))
            with open(part, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(part, os.path.join(tmp, MANIFEST_NAME))
            _atomic.fsync_dir(tmp)
            try:
                os.rename(tmp, final)
            except OSError:
                if not os.path.isdir(final):
                    raise               # lost to a concurrent finalizer?
            _atomic.fsync_dir(base)
            _profiler.incr_counter("ckpt_pod_finalized")
            _bb = _blackbox()
            if _bb is not None:
                _bb.record("ckpt", "pod-finalized", step=step,
                           gen=gen, by_rank=int(by_rank))
            log.warning("pod finalize: committed orphaned step-%d save "
                        "%s (original leader died mid-commit; finalized "
                        "by rank %d)", step, final, by_rank)
            finalized.append(final)
        except (OSError, ValueError, KeyError, CheckpointError) as exc:
            if os.path.isdir(final):
                finalized.append(final)     # a concurrent finalizer won
                continue
            log.warning("pod finalize: could not audit %s (%s); leaving "
                        "it for GC", tmp, exc)
    return finalized


# ---------------------------------------------------------- retention GC

def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True        # EPERM: exists but not ours


def collect_garbage(base: str, keep_last: int,
                    keep_every: Optional[int] = None) -> int:
    """Retention: keep the newest ``keep_last`` VALID checkpoints (plus
    every ``keep_every``-th step forever), delete the remaining valid
    ones, and clear ``.tmp-*`` residues of dead writers. Returns the
    number of checkpoints removed.

    Safety rails: ``keep_last <= 0`` disables deletion entirely; the
    newest valid checkpoint is never deleted; checkpoints that fail the
    validity probe are NEVER auto-deleted (they don't count toward the
    quota either — so GC can never leave only a corrupt checkpoint
    behind) but are logged for the operator."""
    from .. import profiler as _profiler
    removed = 0
    # reap tmp residues of writers that are gone (kill -9 mid-write);
    # pod staging dirs have no live pid to key on — reap them when their
    # step finalized, their generation is over, or they aged out
    cur_gen = os.environ.get("MXNET_TPU_POD_GEN")
    try:
        for name in os.listdir(base):
            m = _TMP_RE.match(name)
            if m and not _pid_alive(int(m.group(1))):
                shutil.rmtree(os.path.join(base, name), ignore_errors=True)
                continue
            pm = _POD_TMP_RE.match(name)
            if pm is None:
                continue
            p = os.path.join(base, name)
            finalized = os.path.isdir(
                os.path.join(base, checkpoint_dir_name(int(pm.group(1)))))
            stale_gen = cur_gen is not None and pm.group(2) != cur_gen
            try:
                aged = (_time.time() - os.path.getmtime(p)
                        ) > _POD_TMP_MAX_AGE
            except OSError:
                aged = False
            if finalized or stale_gen or aged:
                shutil.rmtree(p, ignore_errors=True)
    except OSError:
        pass
    if keep_last is None or keep_last <= 0:
        return 0
    entries = list_checkpoints(base)
    valid = [(s, p) for s, p in entries if probe_valid(p)]
    invalid = [p for s, p in entries if (s, p) not in valid]
    for p in invalid:
        log.warning("retention GC: %s fails the validity probe; leaving "
                    "it for inspection (it does not count toward "
                    "keep-last)", p)
    keep = {p for _s, p in valid[-keep_last:]}
    if keep_every and keep_every > 0:
        keep |= {p for s, p in valid if s % keep_every == 0}
    if valid:
        keep.add(valid[-1][1])
    for _step, path in valid:
        if path in keep:
            continue
        shutil.rmtree(path, ignore_errors=True)
        removed += 1
    if removed:
        _profiler.incr_counter("ckpt_gc_removed", removed)
    return removed

"""Checkpoint scheduling: snapshot capture, the background writer, and
exact-resume payloads.

The CheckFreq (FAST'21) split: a checkpoint is **two** phases with very
different costs. The *snapshot* must be consistent with a step boundary
and is therefore on the training thread — but jax arrays are immutable,
so on a non-donating backend grabbing references IS a complete zero-copy
snapshot, and on donating backends one round of ``jnp.copy`` (an async
device-side dispatch, not a transfer) protects the buffers before the
next fused step invalidates them. The *serialization* (device→host fetch,
checksums, npz encode, fsync) is handed to a bounded background writer
thread, so the step loop resumes after microseconds-to-milliseconds while
tens of megabytes drain to disk behind it. ``ckpt_block_us`` vs
``ckpt_write_us`` counters make the split measurable (and
counter-assertable: tools/perf/checkpoint_bench.py).

``CheckpointManager.save_module`` captures everything exact resume needs:
parameters, aux states, the fused optimizer-state pytree (or the eager
``Updater`` blob), per-parameter update counts, epoch/batch position,
both PRNG chains (the executor's dropout key chain and the global
``mx.random`` chain), and the eval-metric accumulators. ``restore_latest``
returns a :class:`Checkpoint` payload that ``Module.fit(resume_from=...)``
replays so a killed-and-resumed run is bit-identical to an uninterrupted
one (tests/test_checkpoint.py parity suite).
"""
from __future__ import annotations

import errno as _errno
import logging
import os
import queue as _queue_mod
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .. import lockcheck as _lockcheck
from . import format as _format
from .format import (CheckpointCorrupt, CheckpointError,         # noqa: F401
                     CheckpointNotFound)

__all__ = [
    "CheckpointConfig", "CheckpointManager", "Checkpoint",
    "restore_latest", "restore_global_rng",
    "tree_encode", "tree_decode", "key_to_array", "array_to_key",
]

log = logging.getLogger(__name__)


def _blackbox():
    """The flight-recorder gate (one implementation:
    ``profiler.blackbox`` — zero-import when the knob is off).
    Checkpoint commit phases are post-mortem gold: "did the save land
    before the host died" is the first question every recovery asks."""
    from .. import profiler as _profiler
    return _profiler.blackbox()


# -------------------------------------------------- state-tree utilities

def tree_encode(prefix: str, tree, tensors: Dict[str, Any],
                grab: Callable[[Any], Any]):
    """Flatten an optimizer-state tree (None | array | nested tuples)
    into ``tensors`` under dotted keys; returns the json-able structure
    descriptor ``tree_decode`` rebuilds from."""
    if tree is None:
        return None
    if isinstance(tree, tuple):
        return ["tuple", [tree_encode("%s.%d" % (prefix, i), t, tensors,
                                      grab)
                          for i, t in enumerate(tree)]]
    tensors[prefix] = grab(tree)
    return "leaf"


def tree_decode(prefix: str, structure, tensors: Dict[str, Any],
                leaf: Callable[[Any], Any]):
    if structure is None:
        return None
    if structure == "leaf":
        return leaf(tensors[prefix])
    return tuple(tree_decode("%s.%d" % (prefix, i), s, tensors, leaf)
                 for i, s in enumerate(structure[1]))


def key_to_array(key) -> np.ndarray:
    """Raw uint32 array form of a jax PRNG key (either flavor)."""
    import jax
    try:
        import jax.numpy as jnp
        if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
            return np.asarray(jax.random.key_data(key))
    except (AttributeError, TypeError):
        pass
    return np.asarray(key)


def array_to_key(arr: np.ndarray, like):
    """Rebuild a PRNG key from its raw array, matching the flavor of the
    live key ``like`` (typed key array vs raw uint32 vector)."""
    import jax
    import jax.numpy as jnp
    try:
        if jnp.issubdtype(like.dtype, jax.dtypes.prng_key):
            return jax.random.wrap_key_data(jnp.asarray(arr))
    except (AttributeError, TypeError):
        pass
    return jnp.asarray(arr, dtype=like.dtype)


# ----------------------------------------------------------- the config

class CheckpointConfig(object):
    """Declarative checkpoint policy for ``Module.fit(checkpoint=...)``.

    Parameters
    ----------
    directory : str
        Base directory holding ``ckpt-<step>`` subdirectories.
    period_epochs : int
        Auto-save at the end of every N-th epoch (default 1).
    every_n_batches : int, optional
        Additionally save mid-epoch every N batches (the in-flight window
        is drained first so the snapshot is a step boundary).
    keep_last : int, optional
        Retention: newest N checkpoints kept; older ones deleted after
        each successful save. Default: the ``MXNET_TPU_CKPT_KEEP`` knob;
        ``0`` keeps everything.
    keep_every : int, optional
        Additionally keep every checkpoint whose step is a multiple of
        this, forever (coarse history under aggressive keep_last).
    async_save : bool, optional
        Hand serialization to the background writer (default: the
        ``MXNET_TPU_CKPT_ASYNC`` knob). Synchronous saves block the
        caller for the full write.
    save_on_sigterm : bool
        Install a SIGTERM hook during ``fit`` (preemption notice): the
        loop finishes the current batch, saves synchronously, and exits
        with status 143.
    verify_on_load : bool
        Checksum-verify arrays when resuming (default True).
    store_symbol : bool
        Record the symbol JSON in the manifest for provenance.
    queue_depth : int
        Bounded writer queue (each queued snapshot pins one generation of
        parameters until written; depth bounds that memory).
    write_retries : int, optional
        Bounded retry of a failed write on TRANSIENT IO errors
        (EIO/ENOSPC/EINTR) with exponential backoff before the failure
        is recorded/re-raised (default: the ``MXNET_TPU_CKPT_WRITE_RETRIES``
        knob). Each retry counts ``ckpt_write_retry``.
    retry_backoff : float
        Base seconds of the retry backoff (doubles per attempt).
    """

    def __init__(self, directory: str, period_epochs: int = 1,
                 every_n_batches: Optional[int] = None,
                 keep_last: Optional[int] = None,
                 keep_every: Optional[int] = None,
                 async_save: Optional[bool] = None,
                 save_on_sigterm: bool = True,
                 verify_on_load: bool = True,
                 store_symbol: bool = True,
                 queue_depth: int = 2,
                 write_retries: Optional[int] = None,
                 retry_backoff: float = 0.25):
        self.directory = str(directory)
        self.period_epochs = int(period_epochs)
        self.every_n_batches = None if every_n_batches is None \
            else int(every_n_batches)
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.async_save = async_save
        self.save_on_sigterm = bool(save_on_sigterm)
        self.verify_on_load = bool(verify_on_load)
        self.store_symbol = bool(store_symbol)
        self.queue_depth = max(1, int(queue_depth))
        self.write_retries = write_retries
        self.retry_backoff = max(0.0, float(retry_backoff))

    @classmethod
    def coerce(cls, obj) -> "CheckpointConfig":
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, (str, os.PathLike)):
            return cls(os.fspath(obj))
        raise TypeError("checkpoint= accepts a directory path or a "
                        "CheckpointConfig, got %r" % (obj,))

    # knob-backed defaults resolve at use time, not construction time
    def resolved_keep_last(self) -> int:
        if self.keep_last is not None:
            return int(self.keep_last)
        from .. import config as _config
        return int(_config.get("MXNET_TPU_CKPT_KEEP"))

    def resolved_async(self) -> bool:
        if self.async_save is not None:
            return bool(self.async_save)
        from .. import config as _config
        return bool(_config.get("MXNET_TPU_CKPT_ASYNC"))

    def resolved_write_retries(self) -> int:
        if self.write_retries is not None:
            return max(0, int(self.write_retries))
        from .. import config as _config
        return max(0, int(_config.get("MXNET_TPU_CKPT_WRITE_RETRIES")))


# ---------------------------------------------------------- the payload

class Checkpoint(object):
    """A loaded checkpoint: verified host tensors + manifest, with typed
    accessors for what ``fit(resume_from=...)`` consumes."""

    def __init__(self, path: str, tensors: Dict[str, np.ndarray],
                 manifest: Dict[str, Any]):
        self.path = path
        self.tensors = tensors
        self.manifest = manifest

    @property
    def step(self) -> int:
        return int(self.manifest.get("step", 0))

    @property
    def meta(self) -> Dict[str, Any]:
        return self.manifest.get("meta", {})

    # ------------------------------------------------------ loop position
    @property
    def loop(self) -> Dict[str, Any]:
        return self.meta.get("loop") or {}

    @property
    def epoch(self) -> Optional[int]:
        e = self.loop.get("epoch")
        return None if e is None else int(e)

    @property
    def batches_done(self) -> Optional[int]:
        b = self.loop.get("batches_done")
        return None if b is None else int(b)

    @property
    def mid_epoch(self) -> bool:
        return self.batches_done is not None

    @property
    def resume_epoch(self) -> int:
        """First epoch the resumed run should execute (the saved epoch
        itself when the save was mid-epoch, the next one otherwise)."""
        if self.epoch is None:
            return 0
        return self.epoch if self.mid_epoch else self.epoch + 1

    @property
    def metric_state(self):
        return self.meta.get("metric")

    @property
    def data_cursor(self) -> Optional[dict]:
        """The data-plane loader cursor saved with this checkpoint
        (``meta["loop"]["data"]``) — position plus the stream-identity
        fields (seed, batch size, record count) a resuming
        ``mx.data.DataLoader`` validates before fast-forwarding. None
        for checkpoints written without a cursor-capable iterator."""
        cur = self.loop.get("data")
        return dict(cur) if cur else None

    # -------------------------------------------------------- parameters
    def _named(self, prefix: str, names_key: str) -> Dict[str, np.ndarray]:
        names = self.meta.get(names_key)
        if names is None:
            names = [k[len(prefix):] for k in self.tensors
                     if k.startswith(prefix)]
        return {n: self.tensors[prefix + n] for n in names
                if prefix + n in self.tensors}

    def arg_params(self) -> Dict[str, np.ndarray]:
        return self._named("arg:", "param_names")

    def aux_params(self) -> Dict[str, np.ndarray]:
        return self._named("aux:", "aux_names")

    def arg_params_nd(self):
        from .. import ndarray as nd
        # dtype=v.dtype, NOT the nd.array default (which silently casts
        # everything to float32): bit-identical resume must round-trip
        # f64/f16/bf16 parameters at their saved precision
        return {k: nd.array(v, dtype=v.dtype)
                for k, v in self.arg_params().items()}

    def aux_params_nd(self):
        from .. import ndarray as nd
        return {k: nd.array(v, dtype=v.dtype)
                for k, v in self.aux_params().items()}


def restore_latest(directory: str, verify: bool = True) -> Checkpoint:
    """Load the newest valid checkpoint under ``directory`` (corrupt ones
    are skipped with a warning) as a :class:`Checkpoint` payload.

    Orphaned pod staging dirs are finalized-or-abandoned first
    (``format.finalize_staged_pod_saves``): a save whose leader died
    mid-commit must surface here as either the newest checkpoint or
    nothing at all — never a torn manifest."""
    try:
        _format.finalize_staged_pod_saves(directory)
    except Exception:                                      # noqa: BLE001
        log.warning("restore_latest: pod staging audit failed; loading "
                    "the newest committed checkpoint", exc_info=True)
    path, tensors, manifest = _format.load_latest(directory, verify=verify)
    return Checkpoint(path, tensors, manifest)


def restore_global_rng(ckpt: Checkpoint) -> None:
    """Reset the global ``mx.random`` key chain to the snapshot's."""
    raw = ckpt.tensors.get("rng:global_key")
    if raw is None:
        return
    from .. import random as _random
    _random.set_key(array_to_key(raw, like=_random.current_key()))


# ---------------------------------------------------------- the manager

class CheckpointManager(object):
    """Owns one checkpoint directory: bounded async writer, retention GC,
    SIGTERM preemption hook, and the profiler counters/gauges
    (``ckpt_*``) the tests and the bench assert on."""

    def __init__(self, config):
        self.config = CheckpointConfig.coerce(config)
        self._queue: Optional[_queue_mod.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._last_error: Optional[BaseException] = None
        self._preempt = False
        self._closed = False
        self._lock = _lockcheck.Lock(name="checkpoint.manager_lock")
        self._seq: Optional[int] = None

    # ------------------------------------------------------------ status
    @property
    def last_error(self) -> Optional[BaseException]:
        return self._last_error

    @property
    def preempt_requested(self) -> bool:
        return self._preempt

    def request_preempt(self) -> None:
        """Ask the fit loop to checkpoint and exit at the next batch
        boundary (what the SIGTERM hook calls)."""
        self._preempt = True

    def install_sigterm(self) -> Optional[Callable[[], None]]:
        """Install the preemption hook; returns an uninstaller (or None
        when not installable — non-main thread)."""
        import signal

        if threading.current_thread() is not threading.main_thread():
            return None
        prev = signal.getsignal(signal.SIGTERM)

        def _handler(_signum, _frame):
            # async-signal-safe by construction: set ONE flag and return.
            # Taking any lock here (profiler counters, logging) deadlocks
            # the process if the signal lands while the interrupted frame
            # already holds it — ckpt_sigterm is counted on the training
            # thread when the flag is observed (preempt_save)
            self.request_preempt()

        try:
            signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError):
            return None

        def _restore():
            try:
                signal.signal(signal.SIGTERM, prev)
            except (ValueError, OSError, TypeError):
                pass

        return _restore

    def preempt_save(self, module, epoch: Optional[int] = None,
                     batches_done: Optional[int] = None,
                     metric=None, loader_state: Optional[dict] = None
                     ) -> None:
        """The preemption-notice path (``fit`` calls this when it observes
        :attr:`preempt_requested`): drain pending async saves, land the
        final checkpoint synchronously, and shut the writer down. Runs on
        the training thread — the signal handler itself only sets a flag,
        so the ``ckpt_sigterm`` counter is bumped here."""
        from .. import profiler as _profiler
        _profiler.incr_counter("ckpt_sigterm")
        bb = _blackbox()
        if bb is not None:
            # observed-flag context (training thread), NOT the signal
            # handler itself — the flag-only discipline holds
            bb.record("ckpt", "preempt-save", epoch=epoch,
                      batches_done=batches_done)
        self.wait()
        try:
            self.save_module(module, epoch=epoch,
                             batches_done=batches_done,
                             metric=metric, loader_state=loader_state,
                             sync=True)
        except _format.CheckpointPodError as exc:
            # a pod being drained because a PEER died cannot land a
            # collective final save (the commit barrier has a dead
            # member) — that is expected, not fatal: the newest COMPLETE
            # checkpoint is the resume point, and the exit-143 protocol
            # must still run so the supervisor resumes the surviving
            # world instead of misreading a crash
            _profiler.incr_counter("ckpt_preempt_save_failed")
            log.error("preemption save could not complete as a pod unit "
                      "(%s); resuming from the newest complete "
                      "checkpoint instead", exc)
        # raise_errors=False: a STALE async-write failure from earlier in
        # the run (already logged + counted) must not abort the exit-143
        # protocol now that the final synchronous save has landed —
        # orchestrators keyed on 143 would misread a clean preemption
        if self._last_error is not None:
            log.error("preemption save landed, but an earlier async "
                      "checkpoint write had failed: %s", self._last_error)
        self.close(raise_errors=False)

    # ------------------------------------------------------------ saving
    def save_module(self, module, epoch: Optional[int] = None,
                    batches_done: Optional[int] = None, metric=None,
                    loader_state: Optional[dict] = None,
                    sync: Optional[bool] = None) -> int:
        """Snapshot ``module`` (+ loop position + metric accumulators)
        and schedule the write; returns the checkpoint step. The caller
        must have drained any in-flight window first (``fit`` does)."""
        from .. import profiler as _profiler
        t0 = time.perf_counter()
        snap = getattr(module, "_checkpoint_snapshot", None)
        if snap is None:
            raise CheckpointError(
                "%s does not implement _checkpoint_snapshot; subsystem "
                "checkpointing currently requires mx.mod.Module"
                % type(module).__name__)
        # the cheap on-thread phase of the CheckFreq split, visible on the
        # caller's (training) lane next to the step slices
        with _profiler.span("ckpt_snapshot", "ckpt"):
            tensors, meta = snap()
        meta["loop"] = {"epoch": epoch, "batches_done": batches_done}
        if loader_state is not None:
            # the data-plane cursor (mx.data.DataLoader._mx_cursor):
            # position + the stream-identity fields a resume validates
            # (docs/architecture/data_plane.md cursor format)
            meta["loop"]["data"] = dict(loader_state)
        if metric is not None:
            state_fn = getattr(metric, "_ckpt_state", None)
            meta["metric"] = state_fn() if state_fn is not None else None
        if self.config.store_symbol and \
                getattr(module, "symbol", None) is not None:
            try:
                meta["symbol"] = module.symbol.tojson()
            except Exception:                              # noqa: BLE001
                pass     # provenance only — never fail a save over it
        step = int(meta.get("step", 0))
        if "optimizer" not in meta:
            # no optimizer update counter to advance the name: a
            # bound-but-no-optimizer module reports step 0 on EVERY
            # snapshot, and the one-state-per-step dedup would then
            # silently drop every save after the first — substitute a
            # monotonic per-directory sequence
            if self._seq is None:
                existing = _format.list_checkpoints(self.config.directory)
                self._seq = max([s for s, _ in existing] or [0])
            self._seq = max(self._seq + 1, step)
            step = self._seq
            meta["step"] = step
        bb = _blackbox()
        if bb is not None:
            bb.record("ckpt", "save", step=step, epoch=epoch,
                      batches_done=batches_done)
        self._submit(step, tensors, meta, t0, sync=sync)
        return step

    def save(self, tensors: Dict[str, Any], meta: Dict[str, Any],
             step: int, sync: Optional[bool] = None) -> None:
        """Low-level save of an arbitrary tensor dict (the bench and
        power users; ``fit`` goes through :meth:`save_module`)."""
        self._submit(int(step), dict(tensors), dict(meta),
                     time.perf_counter(), sync=sync)

    def _submit(self, step, tensors, meta, t0, sync=None) -> None:
        from .. import profiler as _profiler
        if self._closed:
            raise CheckpointError("CheckpointManager is closed")
        use_async = not sync if sync is not None \
            else self.config.resolved_async()
        if use_async:
            q = self._ensure_writer()
            if q.full():
                _profiler.incr_counter("ckpt_backpressure_wait")
            q.put((step, tensors, meta))
            _profiler.set_gauge("ckpt_queue_depth", q.qsize())
            _profiler.incr_counter("ckpt_save_async")
        else:
            self._write_one(step, tensors, meta)
            _profiler.incr_counter("ckpt_save_sync")
        block_us = int((time.perf_counter() - t0) * 1e6)
        _profiler.incr_counter("ckpt_block_us", block_us)
        _profiler.set_gauge("ckpt_last_block_ms", block_us / 1000.0)

    # ------------------------------------------------------------ writer
    def _ensure_writer(self) -> _queue_mod.Queue:
        with self._lock:
            if self._queue is None:
                self._queue = _queue_mod.Queue(
                    maxsize=self.config.queue_depth)
                self._thread = threading.Thread(
                    target=self._writer_loop, name="ckpt-writer",
                    daemon=True)
                self._thread.start()
            return self._queue

    def _writer_loop(self) -> None:
        from .. import profiler as _profiler
        _profiler.register_thread_lane("ckpt-writer")
        q = self._queue
        while True:
            item = q.get()
            try:
                if item is None:
                    return
                self._write_one(*item)
            except BaseException as exc:                   # noqa: BLE001
                # an async save failure must not kill training mid-run;
                # it IS surfaced: counted, logged, re-raised at close()
                if self._last_error is None:
                    self._last_error = exc
                _profiler.incr_counter("ckpt_write_failed")
                log.error("async checkpoint write failed: %s", exc)
                bb = _blackbox()
                if bb is not None:
                    bb.record("ckpt", "write-failed",
                              error=str(exc)[:500])
                    bb.flush("ckpt-write-failed")
            finally:
                # q.get() already removed the in-flight item, so qsize()
                # IS the number of still-pending saves
                _profiler.set_gauge("ckpt_queue_depth", q.qsize())
                q.task_done()

    # IO errors a retry can plausibly outlive: a flaky block device
    # (EIO), a quota/GC race on shared storage (ENOSPC — retention GC
    # runs between attempts and may have freed space), an interrupted
    # syscall (EINTR). Anything else re-raises immediately.
    _TRANSIENT_ERRNO = frozenset(
        (_errno.EIO, _errno.ENOSPC, _errno.EINTR))

    def _write_one(self, step, tensors, meta) -> None:
        from .. import profiler as _profiler
        t0 = time.perf_counter()
        retries = self.config.resolved_write_retries()
        with _profiler.span("ckpt_write", "ckpt"):
            for attempt in range(retries + 1):
                try:
                    path = _format.write_checkpoint(
                        self.config.directory, step, tensors, meta)
                    break
                except OSError as exc:
                    # write_checkpoint cleans its .tmp-* on the way out,
                    # so a retry starts from a blank slate
                    if exc.errno not in self._TRANSIENT_ERRNO \
                            or attempt >= retries:
                        raise
                    _profiler.incr_counter("ckpt_write_retry")
                    delay = self.config.retry_backoff * (2 ** attempt)
                    log.warning(
                        "checkpoint write hit transient %s (attempt "
                        "%d/%d); retrying in %.2fs",
                        _errno.errorcode.get(exc.errno, exc.errno),
                        attempt + 1, retries + 1, delay)
                    if delay:
                        time.sleep(delay)
        rank, _world = _format.pod_info()
        try:
            arrays_name = _format.ARRAYS_NAME if _world == 1 \
                else "arrays-p%d.npz" % rank
            nbytes = os.path.getsize(os.path.join(path, arrays_name))
        except OSError:
            nbytes = 0
        if rank == 0:
            # in a pod, retention is rank 0's job — concurrent per-host
            # GC of one shared directory would race the validity probes
            _format.collect_garbage(self.config.directory,
                                    self.config.resolved_keep_last(),
                                    self.config.keep_every)
        write_us = int((time.perf_counter() - t0) * 1e6)
        _profiler.incr_counter("ckpt_saved")
        _profiler.incr_counter("ckpt_bytes", nbytes)
        _profiler.incr_counter("ckpt_write_us", write_us)
        _profiler.set_gauge("ckpt_last_write_ms", write_us / 1000.0)
        bb = _blackbox()
        if bb is not None:
            bb.record("ckpt", "committed", step=step,
                      write_ms=round(write_us / 1000.0, 1),
                      bytes=nbytes)

    # --------------------------------------------------------- lifecycle
    def wait(self) -> None:
        """Block until every queued save reached disk."""
        if self._queue is not None:
            self._queue.join()

    def close(self, raise_errors: bool = True) -> None:
        """Drain the queue, stop the writer, and (by default) re-raise
        the first async write failure — a training run must not end
        believing checkpoints exist that never hit disk."""
        if self._closed:
            if raise_errors and self._last_error is not None:
                raise CheckpointError(
                    "checkpoint write failed: %s" % self._last_error
                ) from self._last_error
            return
        self._closed = True
        if self._thread is not None:
            self._queue.join()
            self._queue.put(None)
            self._thread.join(timeout=300.0)
        if raise_errors and self._last_error is not None:
            raise CheckpointError(
                "checkpoint write failed: %s" % self._last_error
            ) from self._last_error

"""``mx.checkpoint`` — asynchronous, crash-safe checkpointing with exact
resume (docs/architecture/checkpoint.md).

What the legacy surface (``model.save_checkpoint`` + ``nd.save``) cannot
do, this subsystem does:

* **crash-safe**: checkpoints are atomic directories (temp + fsync +
  rename) with per-array checksums — ``kill -9`` at any byte never
  destroys the previous checkpoint, and a corrupt/torn candidate is
  detected and skipped at load (``format.py``);
* **asynchronous**: the device→host snapshot is decoupled from
  serialization — the step loop blocks only for reference/copy capture
  while a bounded background writer drains to disk (``manager.py``,
  CheckFreq/Check-N-Run discipline; ``ckpt_block_us`` vs
  ``ckpt_write_us`` counters);
* **complete**: parameters, aux states, fused optimizer-state pytree,
  update counts, epoch/batch position, both PRNG chains, and metric
  accumulators — so ``Module.fit(resume_from=dir)`` reproduces an
  uninterrupted run bit-identically;
* **bounded**: keep-last-N / keep-every-K retention GC that can never
  delete the only valid checkpoint;
* **elastic**: ``read_checkpoint(..., mesh=, layout=)`` re-lays every
  array out onto a DIFFERENT mesh/spec than it was saved from (per-shard
  index windows + checksums in the manifest; ``reshard_tensors``), the
  writer retries transient IO errors with bounded backoff
  (``ckpt_write_retry``), and every recovery path is drivable under
  deterministic fault injection (``mxnet_tpu.faults``,
  docs/architecture/elastic.md).

Typical use::

    import mxnet_tpu as mx
    cfg = mx.checkpoint.CheckpointConfig("ckpts/", every_n_batches=100)
    mod.fit(train_iter, num_epoch=90, checkpoint=cfg)      # auto-saves
    ...
    mod.fit(train_iter, num_epoch=90, resume_from="ckpts/")  # exact resume
"""
from .atomic import atomic_open, fsync_dir, replace_and_sync
from .format import (ARRAYS_NAME, MANIFEST_NAME, CheckpointCorrupt,
                     CheckpointError, CheckpointNotFound,
                     CheckpointPodError,
                     collect_garbage, finalize_staged_pod_saves,
                     list_checkpoints, load_latest,
                     pod_info, probe_valid, read_checkpoint,
                     reshard_tensors, resolve_layout_spec,
                     write_checkpoint)
from .manager import (Checkpoint, CheckpointConfig, CheckpointManager,
                      restore_global_rng, restore_latest)

__all__ = [
    "CheckpointConfig", "CheckpointManager", "Checkpoint",
    "CheckpointError", "CheckpointCorrupt", "CheckpointNotFound",
    "CheckpointPodError",
    "restore_latest", "restore_global_rng",
    "write_checkpoint", "read_checkpoint", "load_latest",
    "reshard_tensors", "resolve_layout_spec",
    "list_checkpoints", "probe_valid", "collect_garbage", "pod_info",
    "finalize_staged_pod_saves",
    "atomic_open", "fsync_dir", "replace_and_sync",
    "ARRAYS_NAME", "MANIFEST_NAME",
]

"""``mx.nd`` — imperative namespace.

Every registered op gets an auto-generated wrapper, mirroring how the
reference builds ``mx.nd.*`` from the C op registry at import time
(reference: python/mxnet/ndarray.py ``_init_ndarray_module``).
"""
from __future__ import annotations

import sys as _sys
import numpy as _np

from ..ops import OP_REGISTRY, get_op
from .ndarray import (
    NDArray, imperative_invoke, array, empty, waitall, concatenate,
    moveaxis, onehot_encode, save, load,
)

__all__ = [
    "NDArray", "array", "empty", "waitall", "concatenate", "moveaxis",
    "onehot_encode", "save", "load", "imperative_invoke",
]


def _make_wrapper(op):
    def wrapper(*args, **kwargs):
        return imperative_invoke(op, *args, **kwargs)
    wrapper.__name__ = op.name
    wrapper.__doc__ = op.__doc__
    return wrapper


_mod = _sys.modules[__name__]
for _name, _op in list(OP_REGISTRY.items()):
    if not hasattr(_mod, _name):
        setattr(_mod, _name, _make_wrapper(_op))
        __all__.append(_name)


# `random` sub-namespace: mx.nd.random.uniform etc. (later reference versions
# moved samplers under mx.nd.random; the 0.11 flat names also exist above)
class _RandomNamespace:
    uniform = staticmethod(getattr(_mod, "_random_uniform"))
    normal = staticmethod(getattr(_mod, "_random_normal"))
    gamma = staticmethod(getattr(_mod, "_random_gamma"))
    exponential = staticmethod(getattr(_mod, "_random_exponential"))
    poisson = staticmethod(getattr(_mod, "_random_poisson"))
    negative_binomial = staticmethod(getattr(_mod, "_random_negative_binomial"))
    generalized_negative_binomial = staticmethod(
        getattr(_mod, "_random_generalized_negative_binomial"))
    multinomial = staticmethod(getattr(_mod, "_sample_multinomial"))
    shuffle = staticmethod(getattr(_mod, "shuffle"))


random = _RandomNamespace()

# later-reference-style alias: mx.nd.contrib.MultiBoxPrior (canonical home is
# mx.contrib.nd, reference python/mxnet/contrib/ndarray.py)
from ..contrib import ndarray as contrib  # noqa: E402


def __getattr__(name):
    """Ops registered after import (rtc.PallasKernel.register, user custom
    kernels) resolve lazily — PEP 562 module fallback."""
    if name in OP_REGISTRY:
        wrapper = _make_wrapper(OP_REGISTRY[name])
        setattr(_mod, name, wrapper)
        return wrapper
    raise AttributeError("module %r has no attribute %r" % (__name__, name))

"""Reference-binary NDArray checkpoint codec (.params files).

Byte-level twin of the reference's serialization
(src/ndarray/ndarray.cc:666-770 + c_api kMXAPINDArrayListMagic):

* file header: uint64 ``0x112`` magic, uint64 reserved 0
* uint64 array count, then per array (NDArray::Save):
  - uint32 ``0xF993FAC8`` (NDARRAY_V1_MAGIC, int64-dim TShape) followed
    by uint32 ndim + int64 dims; OR the legacy V0 form where the first
    uint32 *is* ndim followed by uint32 dims (LegacyTShapeLoad)
  - int32 dev_type, int32 dev_id (Context::Save — ignored on load; we
    always save kCPU=1)
  - int32 mshadow type flag, then the raw little-endian buffer
* uint64 name count, then per name uint64 length + utf-8 bytes
  (dmlc::Stream vector<string>)

Every pre-existing MXNet ``.params`` / ``save_checkpoint`` blob parses
with ``load_bytes``; ``save_bytes`` emits files the reference can read
back — the checkpoint-compatibility half the symbol-JSON loader started.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

__all__ = ["LIST_MAGIC", "NDARRAY_V1_MAGIC", "is_legacy_params",
           "load_bytes", "save_bytes", "strip_arg_aux"]

LIST_MAGIC = 0x112
NDARRAY_V1_MAGIC = 0xF993FAC8

# mshadow type flags (mshadow/base.h kFloat32..kInt64, re-exported via
# include/mxnet/tensor_blob.h)
_FLAG_TO_DTYPE = {0: np.float32, 1: np.float64, 2: np.float16,
                  3: np.uint8, 4: np.int32, 5: np.int8, 6: np.int64}
_DTYPE_TO_FLAG = {np.dtype(v): k for k, v in _FLAG_TO_DTYPE.items()}


def is_legacy_params(head: bytes) -> bool:
    """True when the first bytes carry the reference list magic."""
    return len(head) >= 8 and \
        struct.unpack("<Q", head[:8])[0] == LIST_MAGIC


class _Reader(object):
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("truncated .params file")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]


def _read_ndarray(r: _Reader) -> np.ndarray:
    magic = r.u32()
    if magic == NDARRAY_V1_MAGIC:
        ndim = r.u32()
        shape = struct.unpack("<%dq" % ndim, r.take(8 * ndim)) \
            if ndim else ()
    else:
        # legacy V0: the magic slot is ndim, dims are uint32
        ndim = magic
        if ndim > 32:
            raise ValueError("corrupt .params: implausible ndim %d" % ndim)
        shape = struct.unpack("<%dI" % ndim, r.take(4 * ndim)) \
            if ndim else ()
    if ndim == 0:
        return np.zeros((), np.float32)   # is_none() placeholder
    r.i32()                               # dev_type (load always to host)
    r.i32()                               # dev_id
    flag = r.i32()
    if flag not in _FLAG_TO_DTYPE:
        raise ValueError("unknown mshadow type flag %d" % flag)
    dtype = np.dtype(_FLAG_TO_DTYPE[flag])
    count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
    data = np.frombuffer(r.take(dtype.itemsize * count),
                         dtype=dtype.newbyteorder("<"))
    return data.reshape(shape).astype(dtype, copy=True)


def load_bytes(buf: bytes) -> Union[List[np.ndarray],
                                    Dict[str, np.ndarray]]:
    """Parse a reference ``.params`` blob. Named saves return a dict (in
    file order), anonymous saves a list — mirroring ``mx.nd.load``."""
    r = _Reader(buf)
    if r.u64() != LIST_MAGIC:
        raise ValueError("not a reference NDArray list file")
    r.u64()                               # reserved
    n = r.u64()
    arrays = [_read_ndarray(r) for _ in range(n)]
    n_names = r.u64()
    if n_names == 0:
        return arrays
    if n_names != n:
        raise ValueError("corrupt .params: %d names for %d arrays"
                         % (n_names, n))
    names = [r.take(r.u64()).decode("utf-8") for _ in range(n_names)]
    return dict(zip(names, arrays))


def strip_arg_aux(data: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Drop ``arg:``/``aux:`` prefixes from module-checkpoint keys,
    leaving unprefixed keys alone (shared by the model zoo and
    tools/convert_params.py)."""
    return {k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k: v
            for k, v in data.items()}


def _write_ndarray(parts: List[bytes], arr: np.ndarray):
    arr = np.ascontiguousarray(arr)
    flag = _DTYPE_TO_FLAG.get(arr.dtype)
    if flag is None:
        # the reference format has exactly 7 type flags; silently casting
        # (e.g. uint64 ids or bf16) would corrupt values on a round trip
        raise ValueError(
            "dtype %s has no mshadow type flag in the reference .params "
            "format (supported: %s); cast explicitly before saving"
            % (arr.dtype, sorted(str(np.dtype(d))
                                 for d in _DTYPE_TO_FLAG)))
    parts.append(struct.pack("<I", NDARRAY_V1_MAGIC))
    parts.append(struct.pack("<I", arr.ndim))
    parts.append(struct.pack("<%dq" % arr.ndim, *arr.shape)
                 if arr.ndim else b"")
    parts.append(struct.pack("<ii", 1, 0))   # Context: kCPU, device 0
    parts.append(struct.pack("<i", flag))
    parts.append(arr.astype(arr.dtype.newbyteorder("<")).tobytes())


def save_bytes(data: Union[List[np.ndarray], Dict[str, np.ndarray]]
               ) -> bytes:
    """Serialize to the reference binary layout (readable by any MXNet
    0.8+ ``mx.nd.load``)."""
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    parts = [struct.pack("<QQ", LIST_MAGIC, 0),
             struct.pack("<Q", len(arrays))]
    for a in arrays:
        _write_ndarray(parts, np.asarray(a))
    parts.append(struct.pack("<Q", len(names)))
    for nm in names:
        b = nm.encode("utf-8")
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    return b"".join(parts)

"""NDArray — the imperative array type.

Reference: ``include/mxnet/ndarray.h:77-430`` + ``python/mxnet/ndarray.py``
(SURVEY.md §2.3). The reference NDArray is a ref-counted Chunk with an engine
dependency variable; ops are closures pushed to the threaded engine and the
frontend only blocks on ``asnumpy()``/``wait_to_read()``.

TPU design: NDArray wraps a ``jax.Array``. JAX dispatch is *already* async —
``jax.Array`` is a future and XLA orders operations on the device stream — so
the reference's entire dependency-engine layer (src/engine/, ~2,300 LoC)
collapses into this wrapper (SURVEY.md §2.1 translation note):

* ``wait_to_read`` ≡ ``block_until_ready``
* engine read/write vars ≡ XLA program order (no data races by construction)
* ``FnProperty::kCopyFromGPU`` priority lanes ≡ PJRT transfer streams

Mutation model: JAX buffers are immutable, so "in-place" writes rebind the
wrapped buffer on the *same* NDArray object. Executors and optimizers hold
NDArray references and read ``.data`` at call time, which preserves the
reference's shared-buffer semantics at the object level. (Divergence: a
sliced view does not alias its parent's storage.)
"""
from __future__ import annotations

import inspect
from typing import Any, List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

from .. import autograd as _autograd
from .. import config as _config
from .. import lockcheck as _lockcheck
from .. import profiler as _profiler
from .. import random as _random

# hot-path cache of the engine knob; config.set/reset refreshes it
_SYNC_DISPATCH = _config.get("MXNET_ENGINE_TYPE") == "NaiveEngine"


def _refresh_engine(value):
    global _SYNC_DISPATCH
    _SYNC_DISPATCH = value == "NaiveEngine"


_config.on_change("MXNET_ENGINE_TYPE", _refresh_engine)
from ..base import MXNetError
from ..context import Context, current_context
from ..ops import OP_REGISTRY, OpDef, get_op

__all__ = ["NDArray", "imperative_invoke", "array", "empty", "waitall",
           "concatenate", "moveaxis", "onehot_encode", "save", "load"]


def _ctx_of(data: jax.Array) -> Context:
    try:
        dev = data.device
    except Exception:
        dev = None
    if not isinstance(dev, jax.Device):
        # multi-device (sharded/replicated) array: .device is a Sharding —
        # report the first component device's context
        dev = sorted(data.devices(), key=lambda d: d.id)[0]
    kind = "cpu" if dev.platform == "cpu" else "tpu"
    # Context ids are process-local (context.py jax_device); map the global
    # device id back to its position in this process's local view
    try:
        local = jax.local_devices(backend=dev.platform)
        return Context(kind, local.index(dev))
    except (ValueError, RuntimeError):
        return Context(kind, dev.id)


class NDArray:
    """Multi-device, async n-dimensional array (reference:
    python/mxnet/ndarray.py:138)."""

    __slots__ = ("_data", "_grad", "_grad_req", "_uid", "_version",
                 "__weakref__")
    # numpy should defer to our reflected dunders
    __array_priority__ = 100.0

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            # numpy straight to the target device — routing through
            # jnp.asarray would first land on the *default* device (the
            # real chip when one is attached) and pay a second transfer
            data = np.asarray(data, dtype=dtype)
            dev = (ctx or current_context()).jax_device
            data = jax.device_put(data, dev)
        elif dtype is not None and jnp.dtype(dtype) != data.dtype:
            data = data.astype(jnp.dtype(dtype))
        if ctx is not None and isinstance(data, jax.Array):
            dev = ctx.jax_device
            try:
                cur = data.device
            except Exception:
                cur = None
            if cur is not None and cur != dev:
                data = jax.device_put(data, dev)
        self._data = data
        self._grad: Optional["NDArray"] = None
        self._grad_req: str = "write"
        # tape identity: unique id + in-place mutation counter (autograd.py)
        self._uid: int = _autograd.new_uid()
        self._version: int = 0

    # ------------------------------------------------------------ basics
    @property
    def data(self) -> jax.Array:
        return self._data

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def context(self) -> Context:
        return _ctx_of(self._data)

    ctx = context

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def T(self) -> "NDArray":
        return imperative_invoke(get_op("transpose"), self)

    def __len__(self) -> int:
        return self.shape[0]

    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            np.asarray(self._data), "x".join(map(str, self.shape)), self.context)

    # ------------------------------------------------------- sync points
    def asnumpy(self) -> np.ndarray:
        """Blocking device->host copy (reference: ndarray.py asnumpy /
        SyncCopyToCPU src/ndarray/ndarray.cc:779). A *copy*, like the
        reference: callers may mutate the result without touching the
        device buffer (np.asarray of a jax array is a read-only view)."""
        if _lockcheck._ON:
            _lockcheck.note_sync("asnumpy")
        out = np.asarray(self._data)
        if not out.flags.writeable:
            out = out.copy()
        return out

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def wait_to_read(self) -> None:
        """Block until the async computation producing this array finishes
        (reference: ndarray.h:156 WaitToRead via Engine::WaitForVar)."""
        if _lockcheck._ON:
            _lockcheck.note_sync("wait_to_read")
        self._data.block_until_ready()

    wait_to_write = wait_to_read

    # ------------------------------------------------------- conversions
    def astype(self, dtype) -> "NDArray":
        return imperative_invoke(get_op("Cast"), self, dtype=np.dtype(dtype).name)

    def copy(self) -> "NDArray":
        return NDArray(jnp.asarray(self._data))

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        """(reference: CopyFromTo src/ndarray/ndarray.cc:343-405 — the
        cross-device copy primitive; here one jax.device_put)."""
        if isinstance(other, Context):
            # same-device device_put is a no-op alias; force a real copy so
            # the result never shares a (potentially later-donated) buffer
            return NDArray(jax.device_put(self._data,
                                          other.jax_device).copy())
        other._data = jax.device_put(
            self._data.astype(other.dtype), other.context.jax_device).copy()
        other._version += 1
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device))

    def detach(self) -> "NDArray":
        return NDArray(jax.lax.stop_gradient(self._data))

    # ------------------------------------------------------- autograd
    def attach_grad(self, grad_req: str = "write") -> None:
        """(reference: gluon Parameter/autograd; MarkVariables
        src/ndarray/autograd.cc:78)."""
        grad = NDArray(jnp.zeros_like(self._data))
        _autograd.mark_variables([self], [grad], grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _autograd.backward([self], [out_grad] if out_grad is not None else None,
                           retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------- indexing
    def __getitem__(self, key) -> "NDArray":
        return NDArray(self._data[key])

    def __setitem__(self, key, value):
        val = value._data if isinstance(value, NDArray) else value
        # assignment writes INTO this array: the result must stay on this
        # array's device/sharding regardless of where the source lives
        # (reference: CopyFromTo picks the destination's context)
        sharding = getattr(self._data, "sharding", None)
        if isinstance(key, slice) and key == slice(None):
            if np.isscalar(val):
                # full_like materializes a fresh constant, which eager
                # jax places on the DEFAULT device, not the input's —
                # on rigs whose default backend differs from the
                # array's context this silently migrated every
                # scalar-filled parameter (bias/gamma/beta inits) and
                # produced mixed-device graphs; pin it back
                new = jnp.full_like(self._data, val)
                if sharding is not None and \
                        getattr(new, "sharding", None) != sharding:
                    new = jax.device_put(new, sharding)
                self._data = new
            else:
                # .copy() so a full-slice assign never aliases the source
                # buffer (donated-buffer safety, see copyto)
                new = jnp.broadcast_to(
                    jnp.asarray(val, dtype=self._data.dtype), self.shape
                ).astype(self._data.dtype).copy()
                if sharding is not None and new.sharding != sharding:
                    new = jax.device_put(new, sharding)
                self._data = new
        else:
            if isinstance(val, jax.Array) and sharding is not None \
                    and getattr(val, "sharding", None) != sharding:
                val = jax.device_put(val, sharding)
            self._data = self._data.at[key].set(val)
        # new buffer version: recorded tape entries keep the old value
        self._version += 1

    # ------------------------------------------------------- arithmetic
    def _binop(self, other, opname, scalar_opname, reverse=False):
        if isinstance(other, jax.Array):
            # jax value (possibly a tracer, e.g. a traced lr inside the fused
            # train step): can't concretize to float — go through the
            # broadcasting elementwise op instead of the *_scalar op
            other = NDArray(other)
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return imperative_invoke(get_op(opname), a, b)
        return imperative_invoke(get_op(scalar_opname), self, scalar=float(other))

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binop(o, "elemwise_div", "_rdiv_scalar", reverse=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod", "_mod_scalar")

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binop(o, "broadcast_power", "_rpower_scalar", reverse=True)

    def __neg__(self):
        return imperative_invoke(get_op("negative"), self)

    def __abs__(self):
        return imperative_invoke(get_op("abs"), self)

    def _ibinop(self, o, opname, scalar_opname):
        # route through out=self so the mutation is a *recorded* tape entry —
        # gradients chain through in-place updates (reference keeps the AG
        # node on the array; here the version bump plays that role)
        if isinstance(o, NDArray):
            return imperative_invoke(get_op(opname), self, o, out=self)
        return imperative_invoke(get_op(scalar_opname), self,
                                 scalar=float(o), out=self)

    def __iadd__(self, o):
        return self._ibinop(o, "elemwise_add", "_plus_scalar")

    def __isub__(self, o):
        return self._ibinop(o, "elemwise_sub", "_minus_scalar")

    def __imul__(self, o):
        return self._ibinop(o, "elemwise_mul", "_mul_scalar")

    def __itruediv__(self, o):
        return self._ibinop(o, "elemwise_div", "_div_scalar")

    def __eq__(self, o):
        return self._binop(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    # ------------------------------------------------------- op methods
    def reshape(self, shape=None, **kwargs) -> "NDArray":
        if shape is None:
            shape = kwargs.get("shape")
        if isinstance(shape, int):
            shape = (shape,)
        return imperative_invoke(get_op("Reshape"), self, shape=tuple(shape))


def _attach_op_methods():
    """Expose common ops as NDArray methods, like the reference's generated
    methods on NDArray (python/mxnet/ndarray.py autogen tail)."""
    names = [
        "sum", "mean", "max", "min", "prod", "argmax", "argmin", "clip",
        "abs", "sign", "round", "floor", "ceil", "sqrt", "square", "exp",
        "log", "sigmoid", "tanh", "relu", "softmax", "log_softmax",
        "transpose", "swapaxes", "flatten", "expand_dims", "repeat", "tile",
        "flip", "sort", "argsort", "topk", "pick", "take", "one_hot",
        "broadcast_to", "slice_axis", "squeeze", "astype_", "norm",
        "split", "slice",
    ]
    for nm in names:
        if nm.endswith("_") or nm not in OP_REGISTRY:
            continue
        if hasattr(NDArray, nm):
            continue

        def make(nm):
            def method(self, *args, **kwargs):
                return imperative_invoke(get_op(nm), self, *args, **kwargs)
            method.__name__ = nm
            method.__doc__ = OP_REGISTRY[nm].__doc__
            return method

        setattr(NDArray, nm, make(nm))


# --------------------------------------------------------------- dispatch

def _accepts_is_train(op: OpDef) -> bool:
    cached = getattr(op, "_accepts_is_train", None)
    if cached is None:
        try:
            params = inspect.signature(op.fn).parameters
            cached = "_is_train" in params
        except (TypeError, ValueError):
            cached = False
        op._accepts_is_train = cached
    return cached


def imperative_invoke(op: OpDef, *args, out=None, ctx=None, **attrs):
    """Execute a registered op eagerly (reference: MXImperativeInvoke →
    ImperativeInvokeImpl → PushFCompute, src/c_api/c_api_ndarray.cc:262-423).

    The reference computes engine read/write vars and pushes an async closure;
    here JAX's async dispatch provides the same non-blocking behavior. The
    autograd hook mirrors c_api_ndarray.cc:400-417.
    """
    nd_args = [a for a in args if isinstance(a, NDArray)]
    jax_args = [a._data if isinstance(a, NDArray) else a for a in args]
    attrs = dict(attrs)
    attrs.pop("name", None)  # symbol-layer attr, meaningless imperatively
    if op.needs_rng and attrs.get("_rng") is None:
        attrs["_rng"] = _random.next_key()
    if _accepts_is_train(op):
        attrs.setdefault("_is_train", _autograd.is_training())

    recording = _autograd.is_recording() and not op.is_random
    if recording:
        # capture pre-mutation identities + values (reference saves node
        # inputs at record time, src/ndarray/autograd.cc:129-227).
        # Non-NDArray positionals (e.g. a positional reshape shape) get a
        # None key so replay passes them through as constants — dropping
        # them would re-run the op with defaults in backward.
        in_keys = [(a._uid, a._version) if isinstance(a, NDArray) else None
                   for a in args]
        in_consts = [a._data if isinstance(a, NDArray) else a
                     for a in args]

    _profiling = _profiler.state() == "run"
    if _profiling:
        import time as _time
        _t0 = _time.perf_counter()
    if op.num_inputs == 0 and not nd_args:
        dev = (ctx or current_context()).jax_device
        with jax.default_device(dev):
            outputs = op.fn(*jax_args, **attrs)
    else:
        outputs = op.fn(*jax_args, **attrs)
    if _profiling:
        # block so the event duration is real device time (the reference's
        # engine sync-dispatch profiling mode)
        jax.block_until_ready(outputs)
        _profiler.record_event(op.name, _t0, _time.perf_counter(), "op")
    elif _SYNC_DISPATCH:
        # debug engine: serialize dispatch so failures surface at the op
        # that caused them (reference env_var.md MXNET_ENGINE_TYPE)
        jax.block_until_ready(outputs)
    single = not isinstance(outputs, tuple)
    if single:
        outputs = (outputs,)
    out_nds = [NDArray(o) for o in outputs]

    # aux-state commit (BatchNorm moving stats): trailing num_aux outputs are
    # written back into the trailing num_aux NDArray inputs; the tape entry's
    # trailing outputs are the aux arrays *at their new version* so replay
    # chains through the state update.
    if op.num_aux:
        aux_inputs = nd_args[-op.num_aux:]
        for aux_nd, new_val in zip(aux_inputs, out_nds[-op.num_aux:]):
            aux_nd._data = new_val._data
            aux_nd._version += 1
        result_nds = out_nds[: len(out_nds) - op.num_aux]
        tape_targets = result_nds + aux_inputs
    else:
        result_nds = out_nds
        tape_targets = list(out_nds)

    # hide extra outputs (e.g. BatchNorm mean/var) unless requested
    visible = result_nds
    if op.num_hidden_outputs and not attrs.get("output_mean_var"):
        visible = result_nds[: len(result_nds) - op.num_hidden_outputs]

    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o, v in zip(outs, visible):
            o._data = v._data
            o._version += 1
            tape_targets[tape_targets.index(v)] = o
        ret = out
    elif len(visible) == 1:
        ret = visible[0]
    else:
        ret = visible

    if recording:
        _autograd._record_op(op, attrs, in_keys, in_consts, tape_targets)
    return ret


# --------------------------------------------------------------- helpers

def array(source_array, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (reference: ndarray.py array)."""
    if isinstance(source_array, NDArray):
        return NDArray(source_array._data, ctx=ctx, dtype=dtype)
    # reference semantics: default dtype is mx_real_t (float32) regardless of
    # the source's dtype (python/mxnet/ndarray.py array)
    arr = np.asarray(source_array, dtype=dtype if dtype is not None else np.float32)
    return NDArray(arr, ctx=ctx)


def empty(shape, ctx=None, dtype="float32") -> NDArray:
    return imperative_invoke(get_op("_zeros"), shape=tuple(np.atleast_1d(shape)),
                             dtype=np.dtype(dtype).name, ctx=ctx)


def waitall() -> None:
    """Block until all async computation completes on *every* device
    (reference: Engine::WaitForAll via MXNDArrayWaitAll;
    python/mxnet/ndarray.py:131). XLA executes per-device streams in order,
    so enqueueing one token computation per device and blocking on them
    flushes all previously dispatched work. Local devices only — under
    jax.distributed the global list includes other processes' devices,
    which this process cannot address."""
    tokens = [jax.device_put(jnp.zeros(()), d) for d in jax.local_devices()]
    for t in tokens:
        t.block_until_ready()


def moveaxis(tensor: NDArray, source: int, destination: int) -> NDArray:
    return NDArray(jnp.moveaxis(tensor._data, source, destination))


def concatenate(arrays: Sequence[NDArray], axis: int = 0, always_copy: bool = True) -> NDArray:
    return imperative_invoke(get_op("Concat"), *arrays, dim=axis)


def onehot_encode(indices: NDArray, out: NDArray) -> NDArray:
    """(reference: legacy ndarray.py onehot_encode)."""
    depth = out.shape[1]
    res = imperative_invoke(get_op("one_hot"), indices, depth=depth)
    out._data = res._data
    return out


# --------------------------------------------------------------- save/load

def save(fname: str, data, format: str = "npz") -> None:
    """Save list/dict of NDArrays (reference: src/ndarray/ndarray.cc:668-777
    Save/Load + MXNDArraySave). Default container: npz archive holding each
    tensor plus an ordering manifest — same capability (named/ordered tensor
    checkpoint), TPU-era container. ``format="mxnet"`` writes the
    reference's binary layout instead (magic 0x112 / NDARRAY_V1 records,
    ndarray/legacy_format.py) for interchange with existing MXNet
    tooling; ``load`` autodetects both."""
    if isinstance(data, NDArray):
        data = [data]
    if format == "mxnet":
        from . import legacy_format
        from .. import filesystem as _fs
        from ..checkpoint.atomic import atomic_open
        if isinstance(data, dict):
            blob = {k: np.asarray(v.asnumpy()) for k, v in data.items()}
        else:
            blob = [np.asarray(a.asnumpy()) for a in data]
        with _fs.open_uri(fname, "w") as path:
            with atomic_open(path, "wb") as f:
                f.write(legacy_format.save_bytes(blob))
        return
    if format != "npz":
        raise ValueError("unknown save format %r" % format)
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
        keys = names
    else:
        keys = None
        arrays = list(data)
    payload = {}
    for i, arr in enumerate(arrays):
        key = keys[i] if keys is not None else "__arr_%d__" % i
        payload[key] = np.asarray(arr.asnumpy())
    # fixed-width unicode manifest: loadable with allow_pickle=False so an
    # untrusted checkpoint can never execute code (the reference's binary
    # NDArray format is likewise pickle-free)
    manifest = np.array(
        ["dict" if keys is not None else "list"] + [k for k in payload.keys()],
        dtype=np.str_)
    # atomic: temp file + fsync + rename (checkpoint.atomic) — a crash or
    # kill -9 mid-write can no longer leave a torn archive at the final
    # name, and the previous file survives any failed save
    from .. import filesystem as _fs
    from ..checkpoint.atomic import atomic_open
    with _fs.open_uri(fname, "w") as path:   # s3://, hdfs://, local
        with atomic_open(path, "wb") as f:
            np.savez(f, __manifest__=manifest, **payload)


def load(fname: str):
    """(reference: mx.nd.load; remote URIs stage via mx.filesystem like
    dmlc::Stream). Reads both the npz container and reference-era binary
    ``.params`` blobs (autodetected by magic)."""
    from .. import filesystem as _fs
    with _fs.open_uri(fname, "r") as path:
        with open(path, "rb") as f:
            head = f.read(8)
        from . import legacy_format
        if legacy_format.is_legacy_params(head):
            with open(path, "rb") as f:
                out = legacy_format.load_bytes(f.read())
            if isinstance(out, list):
                return [array(a) for a in out]
            return {k: array(v) for k, v in out.items()}
        with np.load(path, allow_pickle=False) as zf:
            manifest = [str(x) for x in zf["__manifest__"]]
            kind, keys = manifest[0], manifest[1:]
            out = {k: array(zf[k]) for k in keys}
    if kind == "list":
        return [out[k] for k in keys]
    return out


_attach_op_methods()

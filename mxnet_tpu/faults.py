"""Deterministic fault injection — ``MXNET_TPU_FAULTS=<site>@<nth>[:kind]``.

Robustness code that is never exercised is broken code waiting for its
first real outage. This module threads *named injection points* through
the framework's recovery paths so every one of them can be driven
deterministically, in-process or from a subprocess drill, with zero cost
when disarmed (one module-attribute bool per site — no parsing, no
allocation; the CI ``elastic`` job asserts the knobs-off run is
counter-silent).

Spec grammar (comma-separated list)::

    MXNET_TPU_FAULTS=ckpt.arrays_write@1:eio,ckpt.arrays_write@2:enospc
    MXNET_TPU_FAULTS=fit.batch@12:sigterm
    MXNET_TPU_FAULTS=ckpt.read_manifest@1:bitflip

``site`` names an injection point (catalog below), ``nth`` is the
1-based arrival count at that site in this process ("let two saves land,
fail the third"), and ``kind`` picks the failure mode (each site has a
sensible default). The legacy ``MXNET_TPU_CKPT_TEST_CRASH=<point>@<n>``
hook (PR 5) is parsed as an alias for ``ckpt.<point>@<n>:sigkill``.

Fault sites (the catalog ``docs/architecture/elastic.md`` documents):

===================  ============================  =====================
site                 where                         default kind
===================  ============================  =====================
ckpt.arrays_write    writer, start of arrays.npz   eio
ckpt.after_arrays    writer, arrays fsynced        sigkill
ckpt.after_record    writer, shard record          sigkill
                     published (pod saves)
ckpt.after_manifest  writer, manifest fsynced      sigkill
ckpt.before_rename   writer, pre-rename (torn)     sigkill
ckpt.read_manifest   reader, before manifest open  bitflip
ckpt.read_arrays     reader, before npz open       bitflip
fit.batch            fit loop, each batch start    sigterm
host.die             fit loop, each batch start    hostkill
leader.die           fit loop, each batch start    hostkill
                     (arm on the leader's host)
dist.kv              dist.kv_set / dist.kv_get     raise
serve.submit         InferenceServer.submit        raise
serve.decode         GenerativeServer, before      raise
                     each decode step (kills ONE
                     sequence's stream, never the
                     co-resident batch)
serve.evict          GenerativeServer, during      raise
                     sequence eviction (pages are
                     still freed — no leak)
data.worker          data-plane worker process,    sigkill
                     each batch start (the loader
                     respawns it over its
                     undelivered shard range and
                     replays exactly; respawned
                     generations do not re-fire)
data.decode          data-plane decode of one      raise
                     batch (poisons THAT batch —
                     data_batch_poisoned — never
                     the epoch)
replica.die          fleet replica wire, after     sigkill
                     each emitted token frame
                     (the gateway re-prefills the
                     victim's sequences on
                     survivors, at-most-once
                     delivery; respawned replicas
                     do not re-fire)
gateway.route        fleet gateway, at each        raise
                     routing decision (kills ONE
                     request legibly, never the
                     gateway)
===================  ============================  =====================

Failure kinds: ``eio``/``enospc``/``eintr`` raise the matching
``OSError`` (the writer's bounded-retry path treats these as transient);
``raise`` raises :class:`FaultInjected`; ``sigterm``/``sigkill`` deliver
the signal to this process (preemption-notice / hard-kill drills);
``bitflip`` flips one byte in the middle of the site's file and returns
(the subsequent read must *detect* the corruption); ``truncate`` cuts
the site's file in half and returns; ``hostkill`` SIGKILLs the
coordinated supervisor (parent) and then this process — the whole host
vanishes, the pod drill's node-loss model; ``wedge`` stops making
progress while staying alive (the failure only a heartbeat deadline
catches); ``coordsvc`` SIGUSR1s the coordinated supervisor, which
abruptly stops the control-plane KV service it hosts while every host
stays up — the split-brain shape only the probe ring can adjudicate;
``slow`` sleeps ``MXNET_TPU_FAULTS_SLOW_SECS`` (default 0.25) and
returns — the straggler shape: one rank's local work crawls while the
pod stays healthy, detectable only by the per-rank step telemetry
(``MXNET_TPU_OBS_STRAGGLER_RATIO``).

Every fired fault bumps the ``fault_injected`` profiler counter (plus
``fault_injected.<site>``) *before* acting, and — when
``MXNET_TPU_FAULTS_TOUCH=<path>`` names a marker file — appends
``<site>@<arrival>:<kind>`` to it first, so even a SIGKILL/hostkill
drill leaves an attributable, parent-readable trace.
"""
from __future__ import annotations

import errno
import os
import signal
import threading
from typing import Dict, List, Optional

from . import lockcheck as _lockcheck
from .base import MXNetError

__all__ = ["FaultInjected", "ARMED", "fire", "install", "clear",
           "active_specs", "KINDS", "ENV", "LEGACY_ENV"]

ENV = "MXNET_TPU_FAULTS"
LEGACY_ENV = "MXNET_TPU_CKPT_TEST_CRASH"

KINDS = ("eio", "enospc", "eintr", "raise", "sigterm", "sigkill",
         "bitflip", "truncate", "hostkill", "wedge", "coordsvc", "slow")

# the shipped injection points (docs/architecture/elastic.md catalog).
# A spec naming a site outside this set is accepted — new sites must be
# armable before the catalog ships — but WARNED about: a typo'd site
# never fires and the drill vacuously passes as "recovered"
SITES = frozenset((
    "ckpt.arrays_write", "ckpt.after_arrays", "ckpt.after_record",
    "ckpt.after_manifest", "ckpt.before_rename", "ckpt.read_manifest",
    "ckpt.read_arrays", "fit.batch", "serve.submit", "serve.decode",
    "serve.evict", "host.die", "leader.die", "dist.kv",
    # data plane (mxnet_tpu.data, docs/architecture/data_plane.md):
    #   data.worker — fires at a worker process's batch start, default
    #                 sigkill: the loader must detect the corpse,
    #                 respawn generation 1 over the undelivered shard
    #                 range and replay it exactly (respawned workers do
    #                 NOT re-fire this site — progress, not a kill loop)
    #   data.decode — fires in the decode of one batch, default raise:
    #                 poisons THAT batch only (data_batch_poisoned),
    #                 the epoch continues
    "data.worker", "data.decode",
    # serving fleet (mxnet_tpu.fleet, docs/architecture/serving.md):
    #   replica.die   — fires in a replica's token-streaming path after
    #                   the Nth emitted frame, default sigkill: the
    #                   gateway must detect the corpse, re-prefill the
    #                   victim's in-flight sequences on survivors and
    #                   keep token delivery at-most-once (respawned
    #                   replicas do NOT re-fire this site)
    #   gateway.route — fires at the gateway's routing decision,
    #                   default raise: kills exactly ONE request with a
    #                   legible error, never the gateway
    "replica.die", "gateway.route",
))

# kinds that model a HOST dying rather than one process failing
# (multi-host pod drills, docs/architecture/elastic.md):
#   hostkill — SIGKILL the coordinated supervisor (the parent process,
#              only when it marked this child MXNET_TPU_ELASTIC_COORDINATED
#              — never kill an arbitrary parent shell) and then this
#              process: the whole "host" vanishes without cleanup, the
#              honest analog of a node loss, deliverable mid-checkpoint-
#              write via the ckpt.* sites;
#   wedge    — stop making progress while staying alive (sleep forever):
#              the silent failure only a heartbeat deadline catches.
MARKER_ENV = "MXNET_TPU_FAULTS_TOUCH"

_ERRNO = {"eio": errno.EIO, "enospc": errno.ENOSPC, "eintr": errno.EINTR}


class FaultInjected(MXNetError):
    """The error raised by ``kind=raise`` injection sites."""


class _Spec(object):
    __slots__ = ("site", "nth", "kind")

    def __init__(self, site: str, nth: Optional[int], kind: Optional[str]):
        self.site = site
        self.nth = nth
        self.kind = kind

    def __repr__(self):
        return "%s@%s%s" % (self.site, self.nth if self.nth else "*",
                            ":" + self.kind if self.kind else "")


_lock = _lockcheck.Lock(name="faults.lock")
_specs: List[_Spec] = []
_hits: Dict[str, int] = {}
# clear() is final: armed_or_env() must not resurrect env-derived specs
# an explicit clear() disarmed (a one-shot @nth fault re-arming with
# fresh arrival counts would fire a second time)
_env_disarmed = False

# hot-path guard: call sites check `if faults.ARMED:` before calling
# fire() — one attribute read when fault injection is off
ARMED = False


def _parse_one(item: str, default_kind: Optional[str] = None) -> _Spec:
    item = item.strip()
    if "@" in item:
        site, _, rest = item.partition("@")
        nth_s, _, kind = rest.partition(":")
    else:                       # "<site>:<kind>" fires on EVERY arrival
        site, _, kind = item.partition(":")
        nth_s = ""
    if not site:
        raise ValueError("%s: empty site in %r" % (ENV, item))
    kind = kind.strip().lower() or default_kind
    if kind is not None and kind not in KINDS:
        raise ValueError("%s: unknown fault kind %r in %r (known: %s)"
                         % (ENV, kind, item, ", ".join(KINDS)))
    nth = None
    if nth_s.strip():
        nth = int(nth_s)
        if nth < 1:
            raise ValueError("%s: nth must be >= 1 in %r" % (ENV, item))
    if site not in SITES:
        import logging
        logging.getLogger(__name__).warning(
            "%s: %r names no shipped injection site (catalog: %s) — it "
            "will never fire unless a custom site calls fire(%r)",
            ENV, site, ", ".join(sorted(SITES)), site)
    return _Spec(site, nth, kind)


def _parse_env() -> List[_Spec]:
    specs: List[_Spec] = []
    raw = os.environ.get(ENV, "")
    for item in raw.split(","):
        if item.strip():
            specs.append(_parse_one(item))
    legacy = os.environ.get(LEGACY_ENV, "")
    if legacy.strip():
        # PR 5's crash hook, generalized: <point>@<n> == SIGKILL at the
        # n-th arrival of the writer point
        specs.append(_parse_one("ckpt." + legacy.strip(),
                                default_kind="sigkill"))
    return specs


def install(spec: str) -> None:
    """Arm fault injection in-process (tests and the
    ``mx.config.set("MXNET_TPU_FAULTS", ...)`` override): same grammar
    as the env var. Replaces any previously installed spec and resets
    arrival counts; the programmatic spec is authoritative from here on
    — env vars can no longer (re-)arm (``install("")`` disarms for
    good, matching config's override-beats-environment precedence)."""
    global ARMED, _env_disarmed
    parsed = [_parse_one(s) for s in spec.split(",") if s.strip()]
    with _lock:
        _specs[:] = parsed
        _hits.clear()
        ARMED = bool(_specs)
        _env_disarmed = True


def clear() -> None:
    """Disarm all in-process faults and reset arrival counts. Final:
    env-derived specs do not re-arm after an explicit clear()."""
    global ARMED, _env_disarmed
    with _lock:
        _specs[:] = []
        _hits.clear()
        ARMED = False
        _env_disarmed = True


def active_specs() -> List[str]:
    with _lock:
        return [repr(s) for s in _specs]


def armed_or_env() -> bool:
    """COLD-path arming check (checkpoint writer/reader sites): also
    notices the env vars being set *after* import — the runtime-arming
    pattern the legacy ``MXNET_TPU_CKPT_TEST_CRASH`` hook supported
    (set the env, then trigger a save in the same process). Re-parses
    the environment at most once per arming. Hot-path sites
    (``fit.batch``, ``serve.submit``) check :data:`ARMED` alone."""
    global ARMED
    if ARMED:
        return True
    if _env_disarmed:
        return False
    if not (os.environ.get(ENV) or os.environ.get(LEGACY_ENV)):
        return False
    specs = _parse_env()
    with _lock:
        if specs and not _specs and not _env_disarmed:
            _specs[:] = specs
            _hits.clear()
            ARMED = True
    return ARMED


def _blackbox_note(site: str, count: int, kind: str) -> None:
    """Flight-recorder note BEFORE the fault acts: a kill-kind drill's
    post-mortem must carry its own cause of death, and sigkill/hostkill
    leave no later chance to flush. Runs at a normal call site, never a
    signal handler (the signal-unsafe discipline); zero-import when the
    recorder knob is off. ``slow`` fires every batch, so it records
    without forcing a per-batch disk flush."""
    try:
        from . import profiler as _profiler
        _bb = _profiler.blackbox()
        if _bb is None:
            return
        _bb.record("fault", site, arrival=count, kind=kind)
        if kind != "slow":
            _bb.flush("fault:%s@%d:%s" % (site, count, kind))
    except Exception:                                      # noqa: BLE001
        pass    # the recorder must never change drill behavior


def _corrupt_file(path: str, kind: str) -> None:
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size == 0:
        return
    if kind == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
        return
    with open(path, "r+b") as f:          # bitflip
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


def fire(site: str, path: Optional[str] = None,
         default_kind: str = "raise") -> None:
    """Arrival at an injection point: fires the matching spec, if any.

    Call sites guard with ``if faults.ARMED:`` so a disarmed process
    pays one bool read. ``path`` is the file the site is about to
    touch (required by ``bitflip``/``truncate`` kinds)."""
    with _lock:
        if not _specs:
            return
        _hits[site] = _hits.get(site, 0) + 1
        count = _hits[site]
        match = None
        for spec in _specs:
            if spec.site != site:
                continue
            if spec.nth is None or spec.nth == count:
                match = spec
                break
        if match is None:
            return
        kind = match.kind or default_kind
    # act OUTSIDE the lock: raising/killing while holding it would wedge
    # a concurrent arrival on another thread
    from . import profiler as _profiler
    _profiler.incr_counter("fault_injected")
    _profiler.incr_counter("fault_injected.%s" % site)
    _blackbox_note(site, count, kind)
    marker = os.environ.get(MARKER_ENV)
    if marker:
        # parent-readable trace BEFORE acting: even a hostkill/SIGKILL
        # drill leaves an attributable record a supervisor or the drill
        # driver can assert on (O_APPEND: concurrent writers don't tear)
        try:
            with open(marker, "a") as f:
                f.write("%s@%d:%s\n" % (site, count, kind))
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            pass
    if kind in _ERRNO:
        raise OSError(_ERRNO[kind],
                      "injected %s fault at %s" % (kind, site),
                      path or site)
    if kind == "raise":
        raise FaultInjected("injected fault at %s" % site)
    if kind == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if kind == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
        return
    if kind == "hostkill":
        # the whole host dies: take the coordinated supervisor down FIRST
        # (no cleanup, no forwarded signals — exactly what a node loss
        # looks like to the surviving pod), then this process. Guarded by
        # the coordinator's env marker so a drill never SIGKILLs an
        # arbitrary parent (a shell, pytest, an IDE)
        if os.environ.get("MXNET_TPU_ELASTIC_COORDINATED"):
            try:
                os.kill(os.getppid(), signal.SIGKILL)
            except OSError:
                pass
        os.kill(os.getpid(), signal.SIGKILL)
        return
    if kind == "coordsvc":
        # kill ONLY the coordination service while the host stays up —
        # the split-brain shape: SIGUSR1 the coordinated supervisor,
        # whose flag-only handler abruptly stops its control-plane KV
        # server (when it hosts one). This process keeps training; the
        # data plane is untouched. Guarded by the coordinator's env
        # marker like hostkill — never signal an arbitrary parent.
        if os.environ.get("MXNET_TPU_ELASTIC_COORDINATED") \
                and hasattr(signal, "SIGUSR1"):
            try:
                os.kill(os.getppid(), signal.SIGUSR1)
            except OSError:
                pass
        return
    if kind == "wedge":
        # the silent failure: the whole HOST freezes — alive, responsive
        # to nothing, making no progress. The coordinated supervisor is
        # SIGSTOPped (a stopped process is exactly what a stuck host
        # looks like: its liveness beat freezes mid-count), then this
        # process spins in sleep. Detectable only by the heartbeat
        # staleness deadline.
        import time
        if os.environ.get("MXNET_TPU_ELASTIC_COORDINATED"):
            try:
                os.kill(os.getppid(), signal.SIGSTOP)
            except OSError:
                pass
        while True:
            time.sleep(3600)
    if kind == "slow":
        # the straggler shape: this rank's local work crawls while the
        # pod stays alive and healthy — nothing crashes, nothing stalls
        # past a deadline; only the per-rank step telemetry can see it
        import time
        try:
            delay = float(os.environ.get("MXNET_TPU_FAULTS_SLOW_SECS",
                                         "0.25"))
        except ValueError:
            delay = 0.25
        time.sleep(max(0.0, delay))
        return
    if kind in ("bitflip", "truncate"):
        if path is None:
            raise FaultInjected(
                "site %s cannot apply %r (no file)" % (site, kind))
        _corrupt_file(path, kind)
        return
    raise FaultInjected("injected fault at %s (unmapped kind %r)"
                        % (site, kind))


# arm from the environment at import (subprocess drills set the env
# before python starts; in-process tests use install()/clear())
_env_specs = _parse_env()
if _env_specs:
    _specs.extend(_env_specs)
    ARMED = True
del _env_specs

# mx.config.set("MXNET_TPU_FAULTS", spec) is a documented runtime
# override: route it through install() (empty value disarms)
try:
    from . import config as _config
    _config.on_change(ENV, install)
except Exception:                                          # noqa: BLE001
    pass    # config not registered yet (standalone import order)

"""Custom operators written in Python — the user escape hatch.

Reference: ``python/mxnet/operator.py`` (``CustomOp:413``, ``CustomOpProp:480``,
``register:593``) + ``src/operator/custom/custom-inl.h:50-69`` — user code
defines forward/backward over NDArrays, a Prop class declares names/shapes,
and ``register('op_type')`` makes ``mx.nd.Custom``/``mx.sym.Custom`` dispatch
to it by ``op_type``.

TPU design: the user's Python runs on the *host* via ``jax.pure_callback``
(XLA cannot trace arbitrary Python), and the custom gradient plugs into the
program as a ``jax.custom_vjp`` whose backward is a second host callback.
The op integrates with everything built on the registry — Symbol graphs,
Module's fused train step, Gluon blocks, autograd — because "Custom" is an
ordinary registry op. This mirrors how the reference routes custom ops
through the engine as opaque async ops (custom-inl.h Push), at the same
cost model: a host round-trip per call, so use it for glue, not hot loops.

Backend note: host callbacks need a runtime with send/recv support —
standard CPU/GPU/TPU PJRT runtimes have it; remote-tunnel plugins (e.g.
the experimental axon proxy) reject them outright, in which case
callback-based custom ops run on the CPU backend only.

Device-resident fast path: a ``CustomOpProp`` that overrides
``forward_traced`` (and optionally ``backward_traced``) with
jax-traceable code compiles INTO the XLA program — TPU-resident, fused,
no host round trip, works on every backend including callback-less
tunnels. Gradients default to jax autodiff of the traced forward. This
is the path hot-loop custom ops should take; the callback path remains
for arbitrary host Python (reference parity:
src/operator/custom/custom.cc:380-405 kLocal semantics).
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import lockcheck as _lockcheck

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop_class",
           "PythonOp", "NumpyOp", "NDArrayOp"]

_PROP_REGISTRY: Dict[str, type] = {}

# --------------------------------------------------- host-callback thread
# The user's forward/backward runs eager NDArray code, i.e. it re-enters
# jax dispatch. Executing it directly on the runtime's host-callback
# thread can deadlock: that thread is part of the machinery draining the
# async dispatch queue, so an eval-time custom op issued while queued
# train steps drain waits on a queue that can only drain through the
# thread it is blocking (the train_rcnn eval hang). All callback-path
# custom-op Python therefore runs on ONE dedicated worker thread — the
# callback thread only blocks on the future, and the worker's eager
# dispatches proceed like any ordinary frontend thread's. (One thread,
# not a pool: the reference serializes custom ops through its own
# CustomOperator worker the same way, custom-inl.h Push.)

_cb_lock = _lockcheck.Lock(name="operator.cb_lock")
_cb_executor: Optional[ThreadPoolExecutor] = None
_cb_thread_ident: Optional[int] = None


def _run_on_custom_op_thread(fn, *args):
    global _cb_executor
    if threading.get_ident() == _cb_thread_ident:
        return fn(*args)      # nested custom op: run inline, don't self-wait
    if _cb_executor is None:
        with _cb_lock:
            if _cb_executor is None:
                def _note_ident():
                    global _cb_thread_ident
                    _cb_thread_ident = threading.get_ident()
                _cb_executor = ThreadPoolExecutor(
                    1, thread_name_prefix="mxnet_tpu.custom_op",
                    initializer=_note_ident)
    return _cb_executor.submit(fn, *args).result()


class CustomOp(object):
    """Base class for custom operator implementations (reference:
    python/mxnet/operator.py:413)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        """Compute outputs from ``in_data`` into ``out_data`` via
        :meth:`assign`."""
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        """Compute input gradients into ``in_grad`` via :meth:`assign`."""
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Honor the gradient request when writing ``src`` to ``dst``
        (reference: operator.py CustomOp.assign)."""
        if req == "null":
            return
        elif req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise ValueError("invalid req %r" % req)


class CustomOpProp(object):
    """Declares a custom op's interface (reference: operator.py:480).

    Subclass and override ``list_arguments``/``list_outputs``/
    ``infer_shape``/``create_operator``. ``needs_top_grad`` says whether
    backward consumes head gradients (False for loss-style ops).
    """

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        """Default: all inputs share in_shape[0]; every output too
        (reference: operator.py CustomOpProp.infer_shape)."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def need_top_grad(self) -> bool:
        return self.need_top_grad_

    def forward_traced(self, in_data, is_train):
        """OPTIONAL device-resident fast path: return a tuple of outputs
        computed with jax-traceable code (jnp/lax/Pallas) over the input
        jax arrays. Overriding this method commits the op to the traced
        path: it compiles INTO the XLA program — runs on the TPU, fuses
        with its neighbors, and needs no host round-trip (the callback
        path is host-executed and rejected outright by remote-tunnel
        plugins; see docs/new_op.md). Gradients come from jax autodiff
        of this function unless :meth:`backward_traced` is also
        overridden. Leave it un-overridden to use the host-callback
        ``create_operator`` path."""
        raise NotImplementedError

    def backward_traced(self, out_grad, in_data, out_data):
        """OPTIONAL custom gradient for :meth:`forward_traced`: return a
        tuple of input cotangents from jax-traceable code (one per
        input; cotangents for integer inputs are discarded). With
        ``need_top_grad=False`` the incoming ``out_grad`` may be ignored
        (mxnet loss-op semantics). Leave it un-overridden to use jax
        autodiff of ``forward_traced``."""
        raise NotImplementedError

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name: str):
    """Class decorator: ``@mx.operator.register("my_op")`` on a CustomOpProp
    subclass (reference: operator.py:593)."""

    def _reg(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("register() expects a CustomOpProp subclass")
        _PROP_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return _reg


def prop_uses_host_callback(op_type: str) -> bool:
    """True when this op_type's custom op runs user Python through the
    host-callback path (no ``forward_traced`` override). Programs
    embedding such ops must be executed SYNCHRONOUSLY with the frontend
    (executor.py): the callback's user code re-enters eager jax
    dispatch, and if the frontend thread dispatches concurrently while
    the program is in flight the CPU runtime can deadlock — observed as
    the train_rcnn eval hang (frontend blocked in apply_primitive, the
    runtime waiting on the callback, the callback's dispatches waiting
    on the frontend's lock)."""
    cls = _PROP_REGISTRY.get(op_type)
    if cls is None:
        return True        # unknown yet: be conservative
    return cls.forward_traced is CustomOpProp.forward_traced


def symbol_has_host_callback(symbol) -> bool:
    """Scan a Symbol graph for callback-path Custom ops (see
    :func:`prop_uses_host_callback`)."""
    from .symbol.symbol import _topo_order
    for node in _topo_order(symbol._entries):
        if node.op is not None and node.op.name == "Custom":
            op_type = node.attrs.get("op_type")
            if op_type is None or prop_uses_host_callback(str(op_type)):
                return True
    return False


def get_prop_class(op_type: str) -> type:
    try:
        return _PROP_REGISTRY[op_type]
    except KeyError:
        raise KeyError(
            "custom op type %r not registered — decorate its CustomOpProp "
            "with @mx.operator.register(%r)" % (op_type, op_type)) from None


def _make_prop(op_type: str, attrs: Dict[str, Any]) -> CustomOpProp:
    """Instantiate the Prop with the user attrs (the reference passes every
    attr as a string kwarg, operator.py creator glue)."""
    cls = get_prop_class(op_type)
    kwargs = {k: v for k, v in attrs.items()
              if not k.startswith("_") and k != "op_type"}
    return cls(**kwargs)


# --------------------------------------------------------------- registry op


def _np_dtype(dt):
    return np.dtype(dt)


def _custom_impl(arrays, op_type, attrs, is_train):
    import jax
    from . import ndarray as nd

    prop = _make_prop(op_type, attrs)
    arg_names = prop.list_arguments()
    out_names = prop.list_outputs()
    if prop.list_auxiliary_states():
        raise NotImplementedError(
            "auxiliary states on custom ops are not supported yet")
    if len(arrays) != len(arg_names):
        raise ValueError(
            "custom op %r expects %d inputs %s, got %d"
            % (op_type, len(arg_names), arg_names, len(arrays)))

    in_shapes = [tuple(int(d) for d in a.shape) for a in arrays]
    ishapes, oshapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    itypes, otypes, _ = prop.infer_type([_np_dtype(a.dtype) for a in arrays])
    out_avals = tuple(jax.ShapeDtypeStruct(tuple(s), _np_dtype(t))
                      for s, t in zip(oshapes, otypes))
    in_avals = tuple(jax.ShapeDtypeStruct(s, _np_dtype(a.dtype))
                     for s, a in zip(in_shapes, arrays))

    # device-resident fast path: jax-traceable forward (and optionally
    # backward) compile into the program — no host callback at all
    if type(prop).forward_traced is not CustomOpProp.forward_traced:
        def fwd(*xs):
            outs = tuple(prop.forward_traced(list(xs), is_train))
            if len(outs) != len(out_avals) or any(
                    tuple(o.shape) != a.shape or o.dtype != a.dtype
                    for o, a in zip(outs, out_avals)):
                raise ValueError(
                    "forward_traced of %r returned %s, but infer_shape/"
                    "infer_type declare %s" % (
                        op_type,
                        [(tuple(o.shape), str(o.dtype)) for o in outs],
                        [(a.shape, str(np.dtype(a.dtype)))
                         for a in out_avals]))
            return outs

        if type(prop).backward_traced is CustomOpProp.backward_traced:
            if not prop.need_top_grad():
                # the callback path would DROP the incoming cotangent
                # (loss-op semantics); plain autodiff multiplies by it —
                # a ported loss op would silently train on ~zero grads
                raise ValueError(
                    "custom op %r declares need_top_grad=False (loss-op "
                    "semantics) but overrides only forward_traced; "
                    "autodiff would consume the head gradient it promises "
                    "to ignore — override backward_traced too" % op_type)
            outs = fwd(*arrays)     # plain autodiff handles the grads
            return outs if len(outs) != 1 else outs[0]

        import jax.numpy as jnp

        def cot_for(g, x):
            # custom_vjp demands float0 cotangents for integer primals
            if not jnp.issubdtype(jnp.result_type(x.dtype), jnp.inexact):
                return np.zeros(np.shape(x), jax.dtypes.float0)
            return g.astype(x.dtype)

        @jax.custom_vjp
        def run_t(*xs):
            return fwd(*xs)

        def run_t_fwd(*xs):
            outs = fwd(*xs)
            return outs, (xs, outs)

        def run_t_bwd(res, cts):
            xs, outs = res
            gs = prop.backward_traced(list(cts), list(xs), list(outs))
            if gs is None or len(gs) != len(xs):
                raise ValueError(
                    "backward_traced of %r must return one cotangent "
                    "per input (%d); leave it un-overridden to use "
                    "autodiff" % (op_type, len(xs)))
            return tuple(cot_for(g, x) for g, x in zip(gs, xs))

        run_t.defvjp(run_t_fwd, run_t_bwd)
        outs = run_t(*arrays)
        return outs if len(outs) != 1 else outs[0]
    # one operator instance per call site, like the reference's per-executor
    # instance (custom-inl.h CustomOperator); it lives across executions and
    # may carry state
    op_inst = prop.create_operator("cpu(0)", [list(s) for s in ishapes],
                                   itypes)
    n_in = len(arrays)

    def _forward_impl(*xs):
        in_data = [nd.array(np.asarray(x)) for x in xs]
        out_data = [nd.NDArray(np.zeros(s, t))
                    for s, t in zip(oshapes, otypes)]
        op_inst.forward(is_train=is_train, req=["write"] * len(out_data),
                        in_data=in_data, out_data=out_data, aux=[])
        return tuple(o.asnumpy().astype(t, copy=False)
                     for o, t in zip(out_data, otypes))

    def _backward_impl(xs, outs, cts):
        in_data = [nd.array(np.asarray(x)) for x in xs]
        out_data = [nd.array(np.asarray(o)) for o in outs]
        out_grad = [nd.array(np.asarray(c)) for c in cts] \
            if prop.need_top_grad() else []
        in_grad = [nd.NDArray(np.zeros(s, _np_dtype(a.dtype)))
                   for s, a in zip(in_shapes, xs)]
        op_inst.backward(req=["write"] * n_in, out_grad=out_grad,
                         in_data=in_data, out_data=out_data,
                         in_grad=in_grad, aux=[])
        return tuple(g.asnumpy().astype(a.dtype, copy=False)
                     for g, a in zip(in_grad, xs))

    # the runtime's callback thread must never run user NDArray code
    # itself (deadlock — see _run_on_custom_op_thread)
    def host_forward(*xs):
        return _run_on_custom_op_thread(_forward_impl, *xs)

    def host_backward(xs, outs, cts):
        return _run_on_custom_op_thread(_backward_impl, xs, outs, cts)

    @jax.custom_vjp
    def run(*xs):
        return jax.pure_callback(host_forward, out_avals, *xs)

    def run_fwd(*xs):
        outs = jax.pure_callback(host_forward, out_avals, *xs)
        return outs, (xs, outs)

    def run_bwd(res, cts):
        xs, outs = res
        return jax.pure_callback(host_backward, in_avals, xs, outs, cts)

    run.defvjp(run_fwd, run_bwd)
    outs = run(*arrays)
    # serialize with the frontend: an async in-flight callback program +
    # concurrent eager dispatch is the deadlock recipe above. Eager
    # custom-op call sites pay a sync — the documented cost model for
    # the callback path (host round-trip per call) already says "glue,
    # not hot loops".
    jax.block_until_ready(outs)
    return outs if len(outs) != 1 else outs[0]


def _register_custom_op():
    from .ops.registry import register as reg_op, get_op

    @reg_op("Custom", num_inputs=None)
    def custom(*arrays, op_type=None, _is_train=False, **attrs):
        """Dispatch to a registered CustomOpProp by ``op_type`` (reference:
        src/operator/custom/custom.cc + python/mxnet/operator.py glue)."""
        if op_type is None:
            raise ValueError("Custom op needs op_type=")
        return _custom_impl(arrays, op_type, attrs, bool(_is_train))

    def _prop_of(attrs):
        if "op_type" not in attrs:
            raise ValueError("Custom op needs op_type=")
        return _make_prop(attrs["op_type"], attrs)

    opdef = get_op("Custom")
    opdef.num_outputs = lambda attrs: len(_prop_of(attrs).list_outputs())
    opdef.input_names_fn = lambda attrs: list(_prop_of(attrs).list_arguments())


_register_custom_op()


# -------------------------------------------------- legacy frontend classes


class PythonOp(object):
    """Deprecated-but-supported base for the 0.x custom-op style
    (reference: python/mxnet/operator.py:36 PythonOp — predates
    CustomOp/CustomOpProp). Subclass :class:`NumpyOp` or
    :class:`NDArrayOp`; ``get_symbol(*args)`` splices the op into a
    Symbol graph. Internally each instance registers itself as a modern
    CustomOpProp, so the legacy surface rides the same pure_callback +
    custom_vjp machinery as ``mx.sym.Custom``.
    """

    _counter = [0]

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad
        self._op_type = None

    # -- the legacy overridables (reference signatures)
    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError

    def infer_shape(self, in_shape):
        """Returns (in_shapes, out_shapes) — the legacy two-tuple."""
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_

    # -- modern bridge
    def _numpy_mode(self):
        raise NotImplementedError("use NumpyOp or NDArrayOp")

    def _ensure_registered(self):
        if self._op_type is not None:
            return self._op_type
        PythonOp._counter[0] += 1
        op_type = "_legacy_pyop_%d" % PythonOp._counter[0]
        legacy = self
        numpy_mode = self._numpy_mode()

        class _Adapter(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                if numpy_mode:
                    ins = [a.asnumpy() for a in in_data]
                    outs = [o.asnumpy() for o in out_data]
                    legacy.forward(in_data=ins, out_data=outs)
                    for dst, src in zip(out_data, outs):
                        self.assign(dst, "write", src)
                else:
                    legacy.forward(in_data=in_data, out_data=out_data)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                if numpy_mode:
                    ogs = [g.asnumpy() for g in out_grad]
                    ins = [a.asnumpy() for a in in_data]
                    outs = [o.asnumpy() for o in out_data]
                    igs = [g.asnumpy() for g in in_grad]
                    legacy.backward(out_grad=ogs, in_data=ins,
                                    out_data=outs, in_grad=igs)
                    for dst, src in zip(in_grad, igs):
                        self.assign(dst, "write", src)
                else:
                    legacy.backward(out_grad=out_grad, in_data=in_data,
                                    out_data=out_data, in_grad=in_grad)

        class _Prop(CustomOpProp):
            def __init__(self, **_):
                super().__init__(need_top_grad=legacy.need_top_grad())

            def list_arguments(self):
                return list(legacy.list_arguments())

            def list_outputs(self):
                return list(legacy.list_outputs())

            def infer_shape(self, in_shape):
                ishapes, oshapes = legacy.infer_shape(in_shape)
                return ishapes, oshapes, []

            def create_operator(self, ctx, shapes, dtypes):
                return _Adapter()

        _PROP_REGISTRY[op_type] = _Prop
        self._op_type = op_type
        return op_type

    def get_symbol(self, *args, **kwargs):
        """Splice this op into a symbolic graph (reference: PythonOp
        get_symbol -> the Custom symbol)."""
        from . import symbol as sym
        op_type = self._ensure_registered()
        return sym.Custom(*args, op_type=op_type, **kwargs)


class NumpyOp(PythonOp):
    """Legacy numpy custom op (reference: operator.py:143): ``forward``/
    ``backward`` receive numpy arrays and mutate ``out_data``/``in_grad``
    in place."""

    def _numpy_mode(self):
        return True


class NDArrayOp(PythonOp):
    """Legacy NDArray custom op (reference: operator.py:243): same
    contract with NDArrays (assign via ``arr[:] = ...``)."""

    def _numpy_mode(self):
        return False

"""BaseModule — the abstract training-loop owner.

Reference: ``python/mxnet/module/base_module.py`` — ``BaseModule`` (line 80)
defines the high-level API (``fit:376``, ``score:213``, ``predict:300``,
``forward_backward:189``) over the abstract bind/forward/backward/update
primitives its subclasses implement.
"""
from __future__ import annotations

import logging
import os
import time
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from .. import faults as _faults
from .. import metric as _metric
from .. import ndarray as nd
from .. import profiler as _profiler
from ..model import BatchEndParam


# the flight-recorder gate (one implementation: profiler.blackbox —
# zero-import when the knob is off). fit() records only at terminal
# moments (preemption, NANCHECK abort) and epoch boundaries — never
# per batch.
_blackbox = _profiler.blackbox

__all__ = ["BaseModule"]


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


def _check_input_names(symbol, names, typename, throw):
    """(reference: base_module.py:33 _check_input_names)."""
    args = set(symbol.list_arguments())
    for name in names:
        if name in args:
            continue
        msg = "You created Module with Module(..., %s_names=%s) but input with name '%s' is not found in symbol.list_arguments(). Did you mean one of:\n\t%s" % (
            typename, str(names), name, "\n\t".join(sorted(args)))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


class BaseModule(object):
    """The base class of a module (reference: base_module.py:80).

    A module has:
    - binding state (``binded``, ``params_initialized``, ``optimizer_initialized``)
    - data-shape introspection (``data_shapes``, ``label_shapes``, ``output_shapes``)
    - parameter access (``get_params``, ``set_params``, ``init_params``)
    - computation (``forward``, ``backward``, ``update``, ``get_outputs``)
    - and the canonical training loop ``fit``.
    """

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # ---------------------------------------------------------- properties
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self) -> List[str]:
        raise NotImplementedError()

    @property
    def output_names(self) -> List[str]:
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    # ---------------------------------------------------------- parameters
    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        """(reference: base_module.py set_params)."""
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname: str):
        """(reference: base_module.py save_params)."""
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname: str):
        """(reference: base_module.py load_params)."""
        save_dict = nd.load(fname)
        arg_params, aux_params = {}, {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)

    # ---------------------------------------------------------- computation
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def forward_backward(self, data_batch):
        """A convenient function that calls both forward and backward
        (reference: base_module.py:189)."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def install_monitor(self, mon):
        raise NotImplementedError()

    # ---------------------------------------------------------- evaluation
    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Run prediction on ``eval_data`` and evaluate (reference:
        base_module.py:213)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        update_device = getattr(self, "_update_metric_device", None)
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            # device-resident accumulation when the metric supports it:
            # the eval loop then never syncs per batch either (the host
            # fetch happens once, in get_name_value below)
            if update_device is None or \
                    not update_device(eval_metric, eval_batch.label):
                self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric, locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(params)
            actual_num_batch += 1
        if score_end_callback is not None:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """(reference: base_module.py iter_predict)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            yield outputs, nbatch, eval_batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Run prediction, collecting outputs (reference: base_module.py:300)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - pad].copy()
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise ValueError("Cannot merge batches: different numbers "
                                     "of outputs per batch")
            output_list2 = [nd.concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    # ---------------------------------------------------------- training
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, checkpoint=None, resume_from=None,
            grad_accum=None, layout=None, tune=None):
        """Train the module (reference: base_module.py:376 — the canonical
        forward_backward → update → update_metric loop with epoch/batch
        callbacks and checkpointing hooks).

        Crash-safe checkpointing (docs/architecture/checkpoint.md):
        ``checkpoint=`` takes a ``mx.checkpoint.CheckpointConfig`` (or a
        bare directory path) and auto-saves atomic, verifiable checkpoints
        on the configured schedule — every N-th epoch end, optionally
        every N batches mid-epoch (the in-flight window is drained first
        so the snapshot is a step boundary), and on SIGTERM (preemption:
        the current batch finishes, a synchronous save lands, and the
        process exits with status 143). Serialization runs on a bounded
        background writer; the loop blocks only for snapshot capture.
        ``resume_from=`` names a checkpoint directory: the newest VALID
        checkpoint restores parameters, aux states, optimizer state,
        update counts, both PRNG chains, the epoch/batch position, and
        mid-epoch metric accumulators — a killed-and-resumed run is
        bit-identical to an uninterrupted one (tests/test_checkpoint.py).

        On TPU the per-batch body runs as one fused jitted step when the
        subclass provides ``_fit_step`` (Module does); otherwise it falls
        back to forward_backward + update.

        Async pipeline (docs/architecture/async_loop.md): with
        ``MXNET_TPU_ASYNC_WINDOW > 0`` and an async-capable module the hot
        loop dispatches up to K steps ahead (sliding-window sync), metrics
        accumulate as device reductions with the host fetch deferred to
        log boundaries, and batches are device-placed by a background
        prefetch stage — so steady state does ZERO per-batch host syncs
        (counter-asserted: ``loop_host_sync``). A monitor, a host-callback
        CustomOp program, or ``MXNET_TPU_ASYNC_WINDOW=0`` falls back to
        the fully synchronous per-batch loop.

        ``grad_accum=N`` (docs/architecture/program_model.md,
        compile-time control): microbatch gradient accumulation — the
        fused step splits every batch into N equal microbatches run
        through one ``lax.scan`` with gradient carry, so only one
        microbatch's activations are live at a time while the optimizer
        sees the exact full-batch gradient (BatchNorm statistics advance
        per microbatch). Requires a module with a fused step and
        N | batch size.

        ``layout=`` (docs/architecture/parallelism.md): a
        ``parallel.SpecLayout`` — THE multi-chip entry point. The bind
        builds the canonical ``data x fsdp x tp`` mesh, batches shard
        over ``(data, fsdp)``, parameters AND optimizer states shard per
        the layout's name heuristic (FSDP/ZeRO + tensor parallel), and
        GSPMD inserts the collectives. Composes with ``checkpoint=`` /
        ``resume_from=`` (reshard-on-load resolves through the same
        layout funnel). Requires a module implementing ``set_layout``
        (mx.mod.Module).

        ``tune="auto"`` (docs/architecture/tune.md): before binding,
        load or search the tuned configuration for this program
        (``mxnet_tpu.tune``) and apply it — remat / scan / group-update
        / async-window via fit-scoped config overrides (restored when
        fit returns — tuning one fit never reconfigures a later one),
        ``grad_accum`` and ``layout`` through these same arguments when
        the caller left them None (explicit arguments win). ``"static"``
        skips probe subprocesses (model-only pick); default follows the
        ``MXNET_TPU_TUNE`` knob. With a stored config and a warm AOT
        compile cache a restarted fit reaches its first step pre-tuned
        with zero search cost and zero backend compiles.
        """
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform
        from .. import config as _config
        from .. import _fused as _fused_mod
        from .. import random as _random
        if initializer is None:
            # the default initializer draws from the SEEDED mx.random key
            # chain (one split), not the process-global unseeded
            # np.random — two fits after the same mx.random.seed() start
            # from identical weights (the masked-flake source documented
            # in CHANGES PR 4)
            initializer = Uniform(0.01).set_rng(
                _random.derive_numpy_rng("fit_default_init"))

        # --------------------------------------------- checkpoint / resume
        ckpt_mod = None
        ckpt_mgr = None
        resume = None
        uninstall_sigterm = None
        if checkpoint is not None or resume_from is not None:
            from .. import checkpoint as ckpt_mod
        if checkpoint is not None:
            if getattr(self, "_checkpoint_snapshot", None) is None:
                raise MXNetError(
                    "fit(checkpoint=...) requires a module implementing "
                    "_checkpoint_snapshot (mx.mod.Module); %s does not — "
                    "use the legacy epoch_end_callback="
                    "mx.callback.do_checkpoint(...) instead"
                    % type(self).__name__)
            ckpt_mgr = ckpt_mod.CheckpointManager(checkpoint)
        if resume_from is not None:
            resume = ckpt_mod.restore_latest(
                str(resume_from),
                verify=ckpt_mgr.config.verify_on_load if ckpt_mgr else True)
            if arg_params or aux_params:
                self.logger.warning(
                    "fit(resume_from=%s) overrides the explicit "
                    "arg_params/aux_params", resume.path)
            arg_params = resume.arg_params_nd()
            aux_params = resume.aux_params_nd()
            force_init = True
            begin_epoch = resume.resume_epoch
            self.logger.info("resuming from %s (step %d, epoch %d%s)",
                             resume.path, resume.step, begin_epoch,
                             ", batch %d" % resume.batches_done
                             if resume.mid_epoch else "")

        # ------------------------------------------------------------ tune
        # fit(tune="auto"): search (or load) the tuned configuration for
        # this exact program and apply it before anything binds. The knob
        # winners flow through mx.config overrides; grad_accum/layout go
        # through fit's own arguments — but ONLY when the caller left
        # them None (explicit user arguments always win). With a stored
        # config and a warm AOT cache this path costs one JSON read:
        # pre-tuned AND pre-compiled (docs/architecture/tune.md).
        tune_mode = tune if tune is not None \
            else _config.get("MXNET_TPU_TUNE")
        if tune_mode in (True, 1, "on", "1", "yes", "true"):
            tune_mode = "auto"
        tune_knob_snapshot = None
        if tune_mode not in (None, False, 0, "", "off", "0", "no",
                             "false", "none"):
            from .. import tune as _tune   # lazy: only when armed
            budget = _config.get("MXNET_TPU_ANALYZE_HBM_BUDGET") or None
            tuned = _tune.tune_fit(self, train_data, optimizer,
                                   optimizer_params, mode=str(tune_mode),
                                   budget=budget)
            cand = tuned.candidate
            # the overrides are fit-scoped: snapshot the knobs' override
            # state now and restore it in the finally below, so a later
            # fit of a DIFFERENT module with tune off never silently
            # trains under this winner's configuration
            tune_knob_snapshot = _config.snapshot_overrides(cand.knobs())
            for knob, val in cand.knobs().items():
                _config.set(knob, val)
            if grad_accum is None and cand.grad_accum > 1:
                grad_accum = cand.grad_accum
            if layout is None and cand.layout is not None:
                from ..parallel.layout import SpecLayout
                layout = SpecLayout(data=cand.layout[0],
                                    fsdp=cand.layout[1],
                                    tp=cand.layout[2])
            _profiler.incr_counter("tune_applied")
            self.logger.info("fit(tune=%s): applying %s config %s",
                             tune_mode, tuned.source, cand.to_dict())

        if layout is not None:
            lay_setter = getattr(self, "set_layout", None)
            if lay_setter is None:
                raise MXNetError(
                    "fit(layout=...): %s does not support the unified "
                    "SpecLayout (mx.mod.Module does)"
                    % type(self).__name__)
            if force_rebind and getattr(self, "binded", False):
                # the bind below drops the old binding anyway
                # (force_rebind) — drop it first, or set_layout refuses
                # to re-lay a live binding and the documented
                # fit(layout=..., force_rebind=True) path is unreachable
                self.binded = False
            # before bind, so the mesh and every placement honor it
            lay_setter(layout)

        if grad_accum is not None:
            setter = getattr(self, "set_grad_accum", None)
            if setter is not None:
                # before init_optimizer so the fused step builds with it
                setter(grad_accum)
            elif int(grad_accum) > 1:
                raise MXNetError(
                    "fit(grad_accum=%s): %s does not support microbatch "
                    "gradient accumulation" % (grad_accum,
                                               type(self).__name__))

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if resume is not None:
            restore = getattr(self, "_checkpoint_restore", None)
            if restore is not None:
                restore(resume)
            ckpt_mod.restore_global_rng(resume)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        fused = getattr(self, "_fit_step", None)

        # ------------------------------------------------ async loop setup
        window = int(_config.get("MXNET_TPU_ASYNC_WINDOW"))
        async_ok = getattr(self, "_async_capable", lambda: False)
        if monitor is not None or fused is None or not async_ok():
            # a monitor taps per-op values (needs the sync loop); modules
            # without a fused step, or with host-callback programs, must
            # stay synchronous (executor.requires_sync_loop)
            window = 0
        update_device = getattr(self, "_update_metric_device", None)
        inflight = _fused_mod.InflightWindow(window)
        step_token = getattr(self, "_step_token", lambda: None)

        def _loader_hook(it, name):
            # a data-plane hook (_mx_cursor / _mx_fast_forward) on the
            # iterator, looking through one user-applied prefetch
            # wrapper (fit's own wrap happens AFTER these resolve, so
            # only a pre-wrapped PrefetchingIter needs unwrapping)
            fn = getattr(it, name, None)
            if fn is None:
                inner = getattr(it, "iters", None)
                if inner:
                    fn = getattr(inner[0], name, None)
            return fn

        resume_skip_eoe = False
        if resume is not None and resume.mid_epoch:
            # fast-forward the INNER iterator past the batches the
            # interrupted run already consumed BEFORE the device-prefetch
            # wrapper spins up its worker (no compute — the restored
            # params/opt state already reflect those batches, and skipped
            # batches must not be device-placed just to be discarded)
            ff = _loader_hook(train_data, "_mx_fast_forward")
            if ff is not None:
                # a cursor-capable loader (mx.data.DataLoader) seeks
                # straight to the batch index — no decode of skipped
                # batches — after validating the saved cursor's stream
                # identity (seed/batch size/record count) against this
                # run's configuration
                ff(begin_epoch, resume.batches_done,
                   cursor=resume.data_cursor)
            else:
                skip_iter = iter(train_data)
                for _ in range(resume.batches_done):
                    try:
                        next(skip_iter)
                    except StopIteration:
                        resume_skip_eoe = True
                        break
        elif resume is not None:
            # epoch-boundary resume: sync a cursor-capable loader's
            # shuffle epoch (and validate stream identity) so epoch
            # begin_epoch's permutation matches what an uninterrupted
            # run would have drawn
            ff = _loader_hook(train_data, "_mx_fast_forward")
            if ff is not None:
                ff(begin_epoch, 0, cursor=resume.data_cursor)

        wrapped = None
        placer_sink = None
        inner_train_data = train_data
        if window > 0:
            depth = int(_config.get("MXNET_TPU_DEVICE_PREFETCH"))
            placer = getattr(self, "_device_placer", lambda: None)()
            if depth > 0 and placer is not None \
                    and hasattr(train_data, "next") \
                    and getattr(train_data, "provide_data", None):
                sink = getattr(train_data, "_mx_set_device_placer", None)
                if sink is not None:
                    # a placement-capable loader (mx.data.DataLoader) IS
                    # the prefetch stage: its delivered batches already
                    # carry device arrays (per-host device_put onto the
                    # mesh data axis, async H2D) — wrapping it in a
                    # PrefetchingIter would re-copy every batch through
                    # an extra worker thread + queue hop
                    sink(placer)
                    placer_sink = train_data
                else:
                    from ..io.io import PrefetchingIter
                    if not isinstance(train_data, PrefetchingIter):
                        train_data = wrapped = PrefetchingIter(
                            train_data, device_placer=placer,
                            device_prefetch=depth)
                    # an iterator the user already wrapped is used
                    # as-is: stacking a second PrefetchingIter would add
                    # a worker thread and a queue hop just for the
                    # placement stage — those batches are placed in
                    # _load_batch instead

        # the data-plane cursor source for checkpoint manifests; called
        # with fit's CONSUMED count (nbatch) — the loader's own
        # delivered count runs prefetch-depth ahead of consumption and
        # would fast-forward a resume past unseen batches
        cursor_fn = _loader_hook(inner_train_data, "_mx_cursor")

        # the training thread's trace lane: step/checkpoint-snapshot spans
        # land here; metric syncs get their own track (docs/architecture/
        # observability.md lane map)
        _profiler.register_thread_lane("train")

        # coordinated pod mode: the per-host supervisor couples its
        # liveness heartbeat to this file — a training process that stops
        # advancing it (wedged collective, hung iterator) is declared
        # dead by the pod once the staleness deadline passes
        progress_path = os.environ.get("MXNET_TPU_ELASTIC_PROGRESS_FILE")

        def _touch_progress(count):
            try:
                with open(progress_path, "w") as pf:
                    pf.write("%d\n" % count)
            except OSError:
                pass

        # pod straggler telemetry (docs/architecture/observability.md):
        # per-rank step windows published at the epoch log boundary —
        # one KV write per window riding the metric_sync fetch, zero
        # extra per-step host syncs. Gated so a plain single-process
        # fit never imports the obs pod stack (zero-cost,
        # subprocess-proven by the CI multihost job).
        straggler = None
        if (os.environ.get("MXNET_TPU_POD_KV")
                or os.environ.get("DMLC_NUM_WORKER", "1")
                not in ("", "0", "1")) \
                and float(_config.get("MXNET_TPU_OBS_STRAGGLER_RATIO")) > 0:
            from ..obs import straggler as _straggler_mod
            straggler = _straggler_mod.FitPublisher.create()

        completed = False
        if ckpt_mgr is not None and ckpt_mgr.config.save_on_sigterm:
            uninstall_sigterm = ckpt_mgr.install_sigterm()
        ckpt_every_n = ckpt_mgr.config.every_n_batches if ckpt_mgr else None
        ckpt_period = max(1, ckpt_mgr.config.period_epochs) if ckpt_mgr \
            else 1
        try:
            for epoch in range(begin_epoch, num_epoch):
                tic = time.perf_counter()
                eval_metric.reset()
                nbatch = 0
                data_iter = iter(train_data)
                end_of_batch = False
                if resume is not None and resume.mid_epoch \
                        and epoch == begin_epoch:
                    # exact mid-epoch resume: restore the metric
                    # accumulators the snapshot folded to host scalars
                    # (the iterator was fast-forwarded past the consumed
                    # batches before the prefetch wrapper was built)
                    if resume.metric_state is not None:
                        restore_m = getattr(eval_metric, "_ckpt_restore",
                                            None)
                        if restore_m is None or \
                                not restore_m(resume.metric_state):
                            self.logger.warning(
                                "resume: could not restore mid-epoch "
                                "metric state; epoch-%d training metrics "
                                "will only cover the resumed tail", epoch)
                    nbatch = resume.batches_done
                    end_of_batch = resume_skip_eoe
                next_data_batch = None
                if not end_of_batch:
                    try:
                        next_data_batch = next(data_iter)
                    except StopIteration:
                        # resume landed exactly on the epoch's last batch:
                        # nothing left to train, fall through to the
                        # epoch-end processing the interrupted run missed
                        end_of_batch = True
                # the straggler window opens fresh per epoch: the
                # epoch-boundary segment (drain/eval/ckpt) is shared
                # pod work, not a rank-local signal
                t_host_mark = None
                while not end_of_batch:
                    if _faults.ARMED:
                        # deterministic preemption/crash drills: the
                        # elastic suite SIGTERMs/SIGKILLs fit at batch K
                        # (MXNET_TPU_FAULTS=fit.batch@K[:kind]); the pod
                        # drill kills or wedges the whole HOST here
                        # (host.die@K[:hostkill|wedge]); the leader
                        # fail-over drill arms leader.die on the host
                        # carrying the control plane
                        # (leader.die@K[:hostkill|coordsvc])
                        _faults.fire("fit.batch", default_kind="sigterm")
                        _faults.fire("host.die", default_kind="hostkill")
                        _faults.fire("leader.die", default_kind="hostkill")
                    data_batch = next_data_batch
                    # the batch's flow id threads its trace slices across
                    # lanes (prefetch -> place -> step -> metric); batches
                    # the prefetch stage produced already carry one
                    fid = getattr(data_batch, "_mx_flow", None)
                    if fid is None and _profiler.spans_enabled():
                        fid = _profiler.new_flow()
                    if monitor is not None:
                        monitor.tic()
                    if straggler is not None:
                        # LOCAL-work window = previous metric fetch →
                        # this dispatch: the host-side inter-step
                        # segment (fault sleeps, SIGSTOP pulses, input
                        # fetch, callbacks) where a rank's OWN slowness
                        # lands. Collective waits surface inside the
                        # dispatch/metric regions (async dispatch
                        # defers them to the next device sync), which
                        # this window excludes — counting a peer-wait
                        # as local work would equalize every rank's
                        # rate and hide the straggler.
                        _t_ds = time.perf_counter()
                        if t_host_mark is not None:
                            straggler.step(_t_ds - t_host_mark)
                    with _profiler.span("fused_step_dispatch", "step",
                                        flow=fid):
                        if fused is not None and monitor is None:
                            fused(data_batch)
                        else:
                            self.forward_backward(data_batch)
                            self.update()
                    if window > 0:
                        inflight.push(step_token())
                    # metric BEFORE prepare: prepare may switch the current
                    # bucket module, whose outputs are not this batch's
                    with _profiler.span("metric_update", "metric",
                                        flow=fid, lane="metric"):
                        if window > 0 and update_device is not None and \
                                update_device(eval_metric,
                                              data_batch.label):
                            pass  # chained device reduction, no host sync
                        else:
                            if window > 0:
                                # the async loop had to sync for this
                                # metric: visible per-batch pipeline break
                                _profiler.incr_counter("loop_host_sync")
                            self.update_metric(eval_metric,
                                               data_batch.label)
                    if straggler is not None:
                        t_host_mark = time.perf_counter()
                    try:
                        next_data_batch = next(data_iter)
                        self.prepare(next_data_batch)
                    except StopIteration:
                        end_of_batch = True
                    if straggler is not None and getattr(
                            train_data, "_mx_offthread_fetch", False):
                        # re-derived for the streaming data plane: an
                        # OFF-THREAD fetch (PrefetchingIter queue pop,
                        # DataLoader worker-queue pop) is a data-plane
                        # wait — already surfaced as loop_prefetch_stall
                        # / data_stall — not rank-local compute; leaving
                        # it in the window would flag a slow LOADER as a
                        # straggling HOST. An inline iterator's decode
                        # happens on this thread and stays counted as
                        # local work (the PR 13 window semantics).
                        t_host_mark = time.perf_counter()
                    if monitor is not None:
                        monitor.toc_print()
                    if batch_end_callback is not None:
                        batch_end_params = BatchEndParam(epoch=epoch,
                                                         nbatch=nbatch,
                                                         eval_metric=eval_metric,
                                                         locals=locals())
                        for callback in _as_list(batch_end_callback):
                            callback(batch_end_params)
                    nbatch += 1
                    if progress_path:
                        _touch_progress(nbatch)
                    if ckpt_mgr is not None:
                        if ckpt_every_n and nbatch % ckpt_every_n == 0:
                            # the snapshot must be a step boundary: wait
                            # out the in-flight window, then capture (the
                            # cheap phase) and resume the loop while the
                            # writer drains to disk behind it
                            inflight.drain()
                            ckpt_mgr.save_module(
                                self, epoch=epoch, batches_done=nbatch,
                                metric=eval_metric,
                                loader_state=cursor_fn(
                                    epoch=epoch, batches_done=nbatch)
                                if cursor_fn else None)
                        if ckpt_mgr.preempt_requested:
                            # SIGTERM (preemption notice): finish this
                            # batch, land a SYNCHRONOUS save, and exit
                            # with the conventional 128+15 status
                            inflight.drain()
                            ckpt_mgr.preempt_save(
                                self, epoch=epoch, batches_done=nbatch,
                                metric=eval_metric,
                                loader_state=cursor_fn(
                                    epoch=epoch, batches_done=nbatch)
                                if cursor_fn else None)
                            self.logger.warning(
                                "SIGTERM: checkpoint saved at epoch %d "
                                "batch %d; exiting with status 143",
                                epoch, nbatch)
                            bb = _blackbox()
                            if bb is not None:
                                # observed-flag context on the training
                                # thread — never the signal handler
                                bb.record("preempt", "sigterm",
                                          epoch=epoch, batch=nbatch)
                                bb.flush("sigterm")
                            raise SystemExit(143)

                # epoch barrier: wait out in-flight steps so the epoch
                # time is honest and checkpoints/eval see final state
                inflight.drain()
                # the ONE host metric fetch of the epoch (async loop):
                # visible as a metric-lane span at the log boundary
                with _profiler.span("metric_sync", "metric", lane="metric"):
                    name_values = eval_metric.get_name_value()
                if straggler is not None:
                    # the log boundary: the metric fetch just synced the
                    # host, so the window publish adds no device sync —
                    # and rank 0 aggregates the pod's windows here
                    straggler.publish(epoch)
                bb = _blackbox()
                if bb is not None:
                    bb.record("epoch", "end", epoch=epoch, batches=nbatch,
                              metrics={n: round(float(v), 6)
                                       for n, v in name_values})
                for name, val in name_values:
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
                toc = time.perf_counter()
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch, (toc - tic))

                # non-finite step guard (MXNET_TPU_NANCHECK): the ONE
                # host fetch of the device-accumulated isfinite flags,
                # at the same boundary as the metric sync — warn logs,
                # abort raises naming the first non-finite output
                nan_mode = getattr(self, "_nancheck_mode", "off")
                if nan_mode != "off":
                    bad = self._nancheck_poll()
                    if bad is not None:
                        _profiler.incr_counter("loop_nonfinite")
                        msg = ("non-finite values in output %r during "
                               "epoch %d (MXNET_TPU_NANCHECK=%s; a "
                               "diverged loss, inf/NaN inputs, or an "
                               "overflowing update)" % (bad, epoch,
                                                        nan_mode))
                        if nan_mode == "abort":
                            bb = _blackbox()
                            if bb is not None:
                                # NANCHECK abort is a terminal moment:
                                # the window must carry the diverged
                                # output's name
                                bb.record("nancheck", "abort",
                                          output=str(bad), epoch=epoch)
                                bb.flush("nancheck")
                            raise MXNetError(msg)
                        self.logger.warning(msg)

                arg_params_, aux_params_ = self.get_params()
                self.set_params(arg_params_, aux_params_)

                if epoch_end_callback is not None:
                    for callback in _as_list(epoch_end_callback):
                        callback(epoch, self.symbol, arg_params_, aux_params_)

                if eval_data is not None:
                    res = self.score(eval_data, validation_metric,
                                     score_end_callback=eval_end_callback,
                                     batch_end_callback=eval_batch_end_callback,
                                     epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)

                if ckpt_mgr is not None:
                    # epoch-boundary cursor: the NEXT epoch at batch 0,
                    # which is where a resume from this checkpoint starts
                    _eoe_cursor = cursor_fn(epoch=epoch + 1,
                                            batches_done=0) \
                        if cursor_fn else None
                    if (epoch + 1) % ckpt_period == 0:
                        ckpt_mgr.save_module(self, epoch=epoch,
                                             metric=eval_metric,
                                             loader_state=_eoe_cursor)
                    if ckpt_mgr.preempt_requested:
                        ckpt_mgr.preempt_save(self, epoch=epoch,
                                              metric=eval_metric,
                                              loader_state=_eoe_cursor)
                        self.logger.warning(
                            "SIGTERM: checkpoint saved at end of epoch "
                            "%d; exiting with status 143", epoch)
                        bb = _blackbox()
                        if bb is not None:
                            bb.record("preempt", "sigterm", epoch=epoch)
                            bb.flush("sigterm")
                        raise SystemExit(143)

                # after the FINAL epoch a wrapped iterator must not be
                # reset here: the parked prefetch worker would wake and
                # device-place batches of an epoch that never runs
                # (inflating loop_prefetch_placed past one-per-consumed-
                # batch); close() below stops it, then the inner iterator
                # is reset exactly as the synchronous loop would leave it
                if wrapped is None or epoch < num_epoch - 1:
                    train_data.reset()
            completed = True
        finally:
            if uninstall_sigterm is not None:
                uninstall_sigterm()
            if tune_knob_snapshot is not None:
                # drop the tuned knob overrides back to their pre-fit
                # state (override, environment or default): fit(tune=)
                # configures THIS fit, not the process
                _config.restore_overrides(tune_knob_snapshot)
            if placer_sink is not None:
                # detach so a later fit of the same loader against a
                # different module (or no module) never places onto a
                # dead mesh
                placer_sink._mx_set_device_placer(None)
            if wrapped is not None:
                joined = wrapped.close()
                # leave the user's iterator exactly as the synchronous
                # loop would: freshly reset (the prefetch workers may
                # have pre-pulled batches past the last epoch's reset) —
                # but only if the workers actually exited (resetting an
                # iterator a wedged worker is still inside is a data
                # race) and fit is not unwinding an exception (the sync
                # loop leaves the iterator un-reset then, and a reset
                # raising on the same broken source would mask the
                # original error)
                if joined and completed:
                    inner_train_data.reset()
                elif not joined:
                    self.logger.warning(
                        "prefetch worker did not exit within the close() "
                        "deadline; skipping the final reset of the "
                        "training iterator")
            if ckpt_mgr is not None:
                # drain the background writer; surface the first async
                # write failure ONLY on a clean run (raising here while
                # fit is already unwinding would mask the original error)
                ckpt_mgr.close(raise_errors=completed)

    def prepare(self, data_batch):
        """Prepare the module for processing a data batch (no-op by default;
        BucketingModule switches buckets here — reference: base_module.py
        prepare)."""
        pass

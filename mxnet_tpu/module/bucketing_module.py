"""BucketingModule — variable-length workloads without padding waste.

Reference: ``python/mxnet/module/bucketing_module.py:35`` — keeps one Module
per bucket key, all binding against the default bucket's module with
``shared_module=`` so executors reuse one memory pool
(graph_executor.cc:748-749).

TPU design (SURVEY.md §7 "Hard parts — bucketing vs XLA recompilation"):
each bucket is a distinct static shape ⇒ a distinct XLA executable. The
module pool IS the bounded compile cache: parameters are shared by reference
(the same jax.Arrays flow through every bucket's jitted program), so there is
no per-bucket copy and no cross-bucket sync step. Choose bucket sets the way
the reference docs advise (docs/how_to/bucketing.md): a handful of padded
lengths, not one per observed length.
"""
from __future__ import annotations

import logging

from ..context import cpu
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """(reference: bucketing_module.py:35)."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context if context is not None else cpu()
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def _call_sym_gen(self, bucket_key):
        res = self._sym_gen(bucket_key)
        if not isinstance(res, tuple):
            raise ValueError("sym_gen must return (symbol, data_names, "
                             "label_names)")
        return res

    # ------------------------------------------------------------- params
    def get_params(self):
        assert self.binded and self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    # ------------------------------------------------------------- binding
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Bind the default bucket (reference: bucketing_module.py:355 —
        other buckets bind lazily against it)."""
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        symbol, data_names, label_names = \
            self._call_sym_gen(self._default_bucket_key)
        module = Module(symbol, data_names, label_names, logger=self.logger,
                        context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False, shared_module=None,
                    grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """(reference: bucketing_module.py switch_bucket). New buckets share
        the default module's parameter arrays by reference — the TPU form of
        shared_module executor-memory sharing."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        self._curr_module.inputs_need_grad,
                        force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key])
            if self._curr_module.optimizer_initialized:
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module

        if bucket_key != self._curr_bucket_key:
            # share parameter NDArrays by reference with the current module
            curr = self._curr_module
            nxt = self._buckets[bucket_key]
            for n in nxt._param_names:
                if n in curr._exec.arg_dict:
                    nxt._exec.arg_dict[n] = curr._exec.arg_dict[n]
            for n in nxt._aux_names:
                if n in curr._exec.aux_dict:
                    nxt._exec.aux_dict[n] = curr._exec.aux_dict[n]
            nxt._arg_params = {k: nxt._exec.arg_dict[k]
                               for k in nxt._param_names}
            nxt._aux_params = {k: nxt._exec.aux_dict[k]
                               for k in nxt._aux_names}
            nxt.params_initialized = True
            if nxt.optimizer_initialized and curr.optimizer_initialized:
                nxt._fused_states = curr._fused_states
                nxt._fused_num_update = curr._fused_num_update
            self._curr_module = nxt
            self._curr_bucket_key = bucket_key

    def prepare(self, data_batch):
        """Switch to the batch's bucket (reference: bucketing_module.py
        prepare)."""
        bucket_key = getattr(data_batch, "bucket_key", None)
        if bucket_key is None:
            return
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)

    # ------------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    # ------------------------------------------------------------- compute
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.prepare(data_batch)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        self._curr_module.update()

    def _fit_step(self, data_batch):
        self.prepare(data_batch)
        self._params_dirty = True
        self._curr_module._fit_step(data_batch)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for mod in self._buckets.values():
            mod.install_monitor(mon)

"""Module API — the symbolic training frontend.

Reference: ``python/mxnet/module/`` (SURVEY.md §2.14): ``BaseModule`` owns the
canonical ``fit()`` loop (base_module.py:376), ``Module`` binds a Symbol into
executors, ``BucketingModule`` maps variable-length workloads onto a pool of
modules sharing memory.

TPU design: one bound module = one jitted XLA program per entry point; data
parallelism over a context list = batch-sharded inputs over a
``jax.sharding.Mesh`` with XLA inserting the gradient psum (replacing
DataParallelExecutorGroup + KVStore device-comm, SURVEY.md §2.21); the fit
hot loop runs a single fused forward+backward+optimizer-update program with
donated buffers (SURVEY.md §7 "Hard parts": fit() must run fully jitted).
"""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .pipeline_module import PipelineModule

__all__ = ["BaseModule", "Module", "BucketingModule", "SequentialModule",
           "PipelineModule"]

"""PipelineModule: Module-style training with GPipe pipeline stages.

The user surface for pipeline parallelism (the reference's inter-layer
``group2ctx`` story, src/executor/graph_executor.cc:279-393, made a
first-class schedule): the model arrives as a list of stage Symbols, one
per device along a ``pipe`` mesh axis, and the whole schedule — embed
adapter, N repeated stages, loss head, microbatch accumulation, backward,
optimizer update — compiles into ONE jitted SPMD program built on
``parallel.pipeline_apply``.

Stage contract (shapes inferred at ``bind``):

* ``stages[0]`` — input adapter: consumes the ``data`` variable, emits
  the pipeline "wire" (e.g. token embedding). Runs replicated.
* ``stages[1:-1]`` — the repeated body: one free variable named ``x``
  (the wire), wire-shaped output, and **identical parameter structure**
  across stages (equal blocks per stage, the usual pipeline layout);
  their stacked parameters are sharded over the pipe axis.
* ``stages[-1]`` — the head: free variable ``x`` plus any bound label
  variables (e.g. ``softmax_label``); typically ends in a loss op
  (SoftmaxOutput). Runs replicated. Its output is treated like Module's
  forward outputs: backward seeds it with ones, so loss ops' non-vjp
  backward semantics (p - onehot) apply per microbatch and gradients
  accumulate across microbatches — GPipe gradient accumulation.

Limitations (v1): no auxiliary states inside stages (BatchNorm — use
LayerNorm, the pipeline-era norm anyway) and the per-step RNG key is
shared across microbatches (affects Dropout only).

Gradient scaling: heads whose loss op normalizes per batch
(``SoftmaxOutput``/``MakeLoss`` with ``normalization="batch"`` or
``"valid"``) divide by the *microbatch* row count here, so the sum over
M microbatches would be M× the equivalent ``Module`` run; ``step``
folds 1/M back in, making results invariant to ``n_microbatches`` and
matching ``Module`` at the same ``rescale_grad``. (For ``"valid"``
with ``use_ignore`` the 1/M correction is exact only when every
microbatch has the same valid count.)
"""
from __future__ import annotations

import logging
from typing import Dict, List

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd_mod
from .. import optimizer as opt_mod
from ..executor import graph_function
from ..parallel.mesh import make_mesh
from ..parallel.pipeline import pipeline_apply, stack_stage_params

__all__ = ["PipelineModule"]


class PipelineModule(object):
    """Train a stage-split model with a GPipe schedule over a pipe axis.

    Parameters
    ----------
    stages : list of Symbol
        See the module docstring for the stage contract.
    n_microbatches : int
        The bound batch is split into this many microbatches; must divide
        the batch size. More microbatches shrink the pipeline bubble.
    mesh : jax.sharding.Mesh, optional
        Must contain ``axis``; default is a fresh 1-D mesh over all
        devices.
    axis : str
        Pipe mesh-axis name.
    remat : bool
        Recompute stage activations in backward (GPipe memory trade).
    """

    def __init__(self, stages, n_microbatches, mesh=None, axis="pipe",
                 remat=False, logger=logging):
        if len(stages) < 3:
            raise ValueError("need >= 3 stages (adapter, body..., head)")
        self._stages = list(stages)
        self._n_micro = int(n_microbatches)
        self._axis = axis
        self._remat = bool(remat)
        self._mesh = mesh
        self.logger = logger
        self._bound = False
        self._params: Dict[str, Dict[str, object]] = {}
        self._optimizer = None
        self._step_fn = None

    # ------------------------------------------------------------- bind

    def bind(self, data_shapes, label_shapes=None, **_):
        import jax

        n_body = len(self._stages) - 2
        if self._mesh is None:
            self._mesh = make_mesh({self._axis: n_body})
        if self._mesh.shape[self._axis] != n_body:
            raise ValueError(
                "mesh axis %r has %d devices but there are %d body stages"
                % (self._axis, self._mesh.shape[self._axis], n_body))

        self._data_name, data_shape = data_shapes[0][0], data_shapes[0][1]
        self._label_name = label_shapes[0][0] if label_shapes else None
        label_shape = label_shapes[0][1] if label_shapes else None
        B = data_shape[0]
        if B % self._n_micro:
            raise ValueError("batch %d not divisible by %d microbatches"
                             % (B, self._n_micro))
        mb = B // self._n_micro
        self._batch = B
        mb_data = (mb,) + tuple(data_shape[1:])
        mb_label = (mb,) + tuple(label_shape[1:]) if label_shape else None

        # per-stage shape inference walks the wire through the stages
        self._stage_args: List[Dict[str, tuple]] = []
        for i, sym in enumerate(self._stages):
            if sym.list_auxiliary_states():
                raise MXNetError(
                    "PipelineModule stages cannot hold auxiliary states "
                    "(stage %d has %s)" % (i, sym.list_auxiliary_states()))
            feed = {}
            if i == 0:
                feed[self._data_name] = mb_data
            else:
                feed["x"] = self._wire_shape
            if i == len(self._stages) - 1 and self._label_name and \
                    self._label_name in sym.list_arguments():
                feed[self._label_name] = mb_label
            arg_shapes, out_shapes, _ = sym.infer_shape(**feed)
            args = {n: tuple(s) for n, s in
                    zip(sym.list_arguments(), arg_shapes)
                    if n not in feed}
            self._stage_args.append(args)
            if i < len(self._stages) - 1:
                self._wire_shape = tuple(out_shapes[0])
            else:
                self._out_shape = tuple(out_shapes[0])

        # body stages may use per-stage names (b1_*, b2_*, ...): they are
        # matched positionally in sorted-name order against stage 1, and
        # their stacked pytree is keyed by stage 1's names (the body fn)
        body = self._stage_args[1:-1]
        canon = sorted(body[0])
        self._body_order = [sorted(b) for b in body]
        for i, names in enumerate(self._body_order):
            shapes = [body[i][n] for n in names]
            want = [body[0][n] for n in canon]
            if shapes != want:
                raise ValueError(
                    "body stage %d parameter shapes %s do not line up "
                    "with stage 1's %s" % (i + 1, shapes, want))

        self._fns = [graph_function(s) for s in self._stages]
        self._bound = True
        return self

    # ----------------------------------------------------------- params

    def init_params(self, initializer=None, force_init=False):
        from .. import initializer as init_mod
        initializer = initializer or init_mod.Uniform(0.01)
        if self._params and not force_init:
            return
        for i, args in enumerate(self._stage_args):
            stage_params = {}
            for name, shape in args.items():
                arr = nd_mod.zeros(shape, dtype=np.float32)
                initializer(init_mod.InitDesc(name, {}), arr)
                stage_params[name] = np.asarray(arr.asnumpy())
            self._params[i] = stage_params

    def get_params(self):
        """Per-stage parameter dicts, reflecting training: after
        init_optimizer the authoritative copies live on device
        (fit_step's donated jit updates them), so read those back."""
        if getattr(self, "_dev_params", None) is None:
            return {i: dict(p) for i, p in self._params.items()}
        n_stage = len(self._stages)
        out = {0: {k: np.asarray(v)
                   for k, v in self._dev_params["first"].items()}}
        canon = sorted(self._stage_args[1])
        for i in range(1, n_stage - 1):
            names = self._body_order[i - 1]
            out[i] = {n: np.asarray(self._dev_params["body"][c][i - 1])
                      for c, n in zip(canon, names)}
        out[n_stage - 1] = {k: np.asarray(v)
                            for k, v in self._dev_params["last"].items()}
        return out

    _LOSS_OPS = ("SoftmaxOutput", "MakeLoss", "LinearRegressionOutput",
                 "MAERegressionOutput", "LogisticRegressionOutput",
                 "SVMOutput")

    def _head_normalizes(self):
        """True when the head stage's loss ops normalize their gradient
        by row count per call (so per microbatch, not per batch):
        SoftmaxOutput/MakeLoss with normalization batch/valid (ops/nn.py
        _softmax_output_bwd). A head mixing normalized and unnormalized
        loss ops has no single 1/M correction — reject it."""
        from ..symbol.symbol import _topo_order
        normed, unnormed = [], []
        for node in _topo_order(self._stages[-1]._entries):
            if node.op is None or node.op.name not in self._LOSS_OPS:
                continue
            if node.attrs.get("normalization") in ("batch", "valid"):
                normed.append(node.name)
            else:
                unnormed.append(node.name)
        if normed and unnormed:
            raise MXNetError(
                "head stage mixes per-batch-normalized loss ops %s with "
                "unnormalized ones %s; the GPipe microbatch-accumulation "
                "correction (1/n_microbatches) cannot apply to both — "
                "use one normalization mode across the head's losses"
                % (normed, unnormed))
        return bool(normed)

    # -------------------------------------------------------- optimizer

    def init_optimizer(self, optimizer="sgd", optimizer_params=None):
        import jax
        import jax.numpy as jnp

        if not self._bound:
            raise MXNetError("bind before init_optimizer")
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params or {})
            # per-example gradient scaling, same convention as
            # Module.init_optimizer (module.py:345-351): head grads are
            # p-onehot per microbatch, summed over microbatches
            optimizer_params.setdefault("rescale_grad", 1.0 / self._batch)
            optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer = optimizer

        fns = self._fns
        n_stage = len(self._stages)
        data_name, label_name = self._data_name, self._label_name
        mesh, axis, n_micro = self._mesh, self._axis, self._n_micro
        remat = self._remat
        # microbatch-accumulation invariance (see module docstring): a
        # per-batch-normalized loss head divides by mb rows, not B, so
        # the accumulated grads carry an extra factor of M — undo it
        acc_scale = 1.0 / n_micro if self._head_normalizes() else 1.0

        def run_sym(fn, extra):
            def call(params, key):
                outs, _ = fn({**params, **extra}, {}, key, True)
                return outs[0]
            return call

        def first_fn(p, raw):
            outs, _ = fns[0]({**p, data_name: raw[data_name]}, {},
                             p["__key__"], True)
            return outs[0]

        def stage_fn(p, x):
            outs, _ = fns[1]({**{k: v for k, v in p.items()
                                 if k != "__key__"}, "x": x}, {},
                             p["__key__"], True)
            return outs[0]

        def last_fn(p, y, raw):
            feed = {k: v for k, v in p.items() if k != "__key__"}
            feed["x"] = y
            if label_name is not None:
                feed[label_name] = raw[label_name]
            outs, _ = fns[n_stage - 1](feed, {}, p["__key__"], True)
            return outs[0]

        def loss_like(params, inputs, key):
            fp = dict(params["first"]); fp["__key__"] = key
            lp = dict(params["last"]); lp["__key__"] = key
            sp = dict(params["body"]); sp["__key__"] = \
                jnp.broadcast_to(key, (n_stage - 2,) + key.shape)
            outs = pipeline_apply(
                stage_fn, sp, inputs, mesh=mesh, axis=axis,
                first_fn=first_fn, first_params=fp,
                last_fn=last_fn, last_params=lp, remat=remat)
            return jnp.sum(outs.astype(jnp.float32)), outs

        opt = self._optimizer

        def step(params, states, inputs, key, lr, t):
            grads, outs = jax.grad(loss_like, has_aux=True)(
                params, inputs, key)
            if acc_scale != 1.0:
                grads = jax.tree_util.tree_map(
                    lambda g: g * acc_scale, grads)
            new_p, new_s = {}, {}
            idx = 0
            for grp in ("first", "body", "last"):
                gp, gs = {}, {}
                for name in sorted(params[grp]):
                    w, s = opt.raw_update(
                        idx, params[grp][name], grads[grp][name],
                        states[grp][name], lr=lr, t=t)
                    gp[name], gs[name] = w, s
                    idx += 1
                new_p[grp], new_s[grp] = gp, gs
            return outs, new_p, new_s

        self._step_jit = jax.jit(step, donate_argnums=(0, 1))

        # assemble device param pytrees: body stacked under stage 1's
        # names (positional match in sorted order), first/last flat
        import jax.numpy as jnp
        canon = sorted(self._stage_args[1])
        body_trees = []
        for i in range(1, n_stage - 1):
            names = self._body_order[i - 1]
            body_trees.append({c: jnp.asarray(self._params[i][n])
                               for c, n in zip(canon, names)})
        self._dev_params = {
            "first": {k: jnp.asarray(v)
                      for k, v in self._params[0].items()},
            "body": stack_stage_params(body_trees),
            "last": {k: jnp.asarray(v)
                     for k, v in self._params[n_stage - 1].items()},
        }

        # optimizer state per leaf (momentum etc.); SGD w/o momentum -> None
        def state_for(w):
            s = opt.create_state(0, nd_mod.array(np.zeros(w.shape,
                                                          np.float32)))
            return jax.tree_util.tree_map(
                lambda x: jnp.zeros(w.shape, jnp.float32)
                if hasattr(x, "shape") else x, s,
                is_leaf=lambda x: x is None or hasattr(x, "shape"))

        self._dev_states = jax.tree_util.tree_map(
            state_for, self._dev_params,
            is_leaf=lambda x: hasattr(x, "shape"))
        self._t = 0

    # ------------------------------------------------------------- step

    def fit_step(self, data_batch):
        """One pipelined train step; returns the head outputs
        (n_microbatches, mb, ...)."""
        import jax
        import jax.numpy as jnp

        if self._optimizer is None:
            raise MXNetError("init_optimizer before fit_step")
        B = self._batch
        M = self._n_micro
        x = np.asarray(data_batch.data[0].asnumpy())
        inputs = {self._data_name:
                  jnp.asarray(x.reshape((M, B // M) + x.shape[1:]))}
        if self._label_name is not None:
            y = np.asarray(data_batch.label[0].asnumpy())
            inputs[self._label_name] = jnp.asarray(
                y.reshape((M, B // M) + y.shape[1:]))
        key = jax.random.PRNGKey(self._t)
        # Module's fused-step lr convention (module.py:530-537):
        # advance num_update and honor the lr scheduler
        self._t += 1
        self._optimizer.num_update = self._t
        if getattr(self._optimizer, "lr_scheduler", None) is not None:
            lr = self._optimizer.lr_scheduler(self._t)
        else:
            lr = self._optimizer.lr
        outs, self._dev_params, self._dev_states = self._step_jit(
            self._dev_params, self._dev_states, inputs, key,
            jnp.asarray(lr, jnp.float32),
            jnp.asarray(self._t, jnp.int32))
        return outs

    def fit(self, train_iter, num_epoch=1, eval_metric=None):
        """Minimal fit loop: fit_step per batch (metric optional)."""
        from .. import metric as metric_mod
        if eval_metric is not None and not hasattr(eval_metric, "update"):
            eval_metric = metric_mod.create(eval_metric)
        for epoch in range(num_epoch):
            train_iter.reset()
            for batch in train_iter:
                outs = self.fit_step(batch)
                if eval_metric is not None:
                    # (M,) + per-microbatch head shape -> flatten the
                    # microbatch axis into the leading row axis
                    flat = nd_mod.array(np.asarray(outs).reshape(
                        (-1,) + self._out_shape[1:]))
                    eval_metric.update(batch.label, [flat])
            if eval_metric is not None:
                self.logger.info("Epoch[%d] %s", epoch,
                                 eval_metric.get())
        return self

"""PipelineModule: Module-style training with pipelined stages.

The user surface for pipeline parallelism (the reference's inter-layer
``group2ctx`` story, src/executor/graph_executor.cc:279-393, made a
first-class schedule): the model arrives as a list of stage Symbols, one
per device along a ``pipe`` mesh axis, and the whole schedule — embed
adapter, N body stages, loss head, microbatch accumulation, backward,
optimizer update — compiles into ONE jitted SPMD program built on
``parallel.pipeline_apply`` (GPipe) or ``parallel.pipeline_1f1b``
(one-forward-one-backward).

Stage contract (shapes inferred at ``bind``):

* ``stages[0]`` — input adapter: consumes the ``data`` variable, emits
  the pipeline "wire" (e.g. token embedding). Runs replicated.
* ``stages[1:-1]`` — the body: one free variable named ``x`` (the
  wire) and wire-shaped output. Bodies with **identical parameter
  structure** run on the fast path (stacked parameters sharded over
  the pipe axis); heterogeneous bodies (unequal shapes/structures) are
  supported too — each device runs its own stage branch and the
  per-stage parameter trees ride replicated (ragged trees cannot
  shard), which pipelines activations but not parameter memory.
* ``stages[-1]`` — the head: free variable ``x`` plus any bound label
  variables (e.g. ``softmax_label``); typically ends in a loss op
  (SoftmaxOutput). Runs replicated. Its output is treated like Module's
  forward outputs: backward seeds it with ones, so loss ops' non-vjp
  backward semantics (p - onehot) apply per microbatch and gradients
  accumulate across microbatches.

Schedules (``schedule=``):

* ``"gpipe"`` (default) — all-forward-then-all-backward via jax
  autodiff of the forward scan; activation residuals for all M
  microbatches stay live. Restrictions: no auxiliary states in stages
  (BatchNorm), one RNG key shared across microbatches (Dropout).
* ``"1f1b"`` — hand-scheduled one-forward-one-backward lattice
  (PipeDream-flush class): activation memory is O(n_stages) instead of
  O(M), stages MAY hold auxiliary states (BatchNorm running stats
  advance once per microbatch), and the RNG key is folded with the
  microbatch index (per-microbatch Dropout, replayed exactly in the
  backward recompute). Parameters ride replicated (see above).

Gradient scaling: heads whose loss op normalizes per batch
(``SoftmaxOutput``/``MakeLoss`` with ``normalization="batch"`` or
``"valid"``) divide by the *microbatch* row count here, so the sum over
M microbatches would be M× the equivalent ``Module`` run; ``step``
folds 1/M back in, making results invariant to ``n_microbatches`` and
matching ``Module`` at the same ``rescale_grad``. (For ``"valid"``
with ``use_ignore`` the 1/M correction is exact only when every
microbatch has the same valid count.)
"""
from __future__ import annotations

import logging
from typing import Dict, List

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd_mod
from .. import optimizer as opt_mod
from ..executor import graph_function
from ..parallel.mesh import make_mesh
from ..parallel.pipeline import (pipeline_apply, pipeline_1f1b,
                                 stack_stage_params)

__all__ = ["PipelineModule"]


class PipelineModule(object):
    """Train a stage-split model with a pipelined schedule over a pipe
    axis.

    Parameters
    ----------
    stages : list of Symbol
        See the module docstring for the stage contract.
    n_microbatches : int
        The bound batch is split into this many microbatches; must divide
        the batch size. More microbatches shrink the pipeline bubble.
    mesh : jax.sharding.Mesh, optional
        Must contain ``axis``; default is a fresh 1-D mesh over all
        devices.
    axis : str
        Pipe mesh-axis name.
    schedule : "gpipe" or "1f1b"
        See the module docstring.
    remat : bool
        GPipe only: recompute stage activations in backward
        (``jax.checkpoint``). 1F1B always recomputes from saved stage
        inputs — that is its design.
    """

    def __init__(self, stages, n_microbatches, mesh=None, axis="pipe",
                 schedule="gpipe", remat=False, logger=logging):
        if len(stages) < 3:
            raise ValueError("need >= 3 stages (adapter, body..., head)")
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError("schedule must be 'gpipe' or '1f1b', got %r"
                             % (schedule,))
        self._stages = list(stages)
        self._n_micro = int(n_microbatches)
        self._axis = axis
        self._schedule = schedule
        self._remat = bool(remat)
        self._mesh = mesh
        self.logger = logger
        self._bound = False
        self._params: Dict[str, Dict[str, object]] = {}
        self._aux: Dict[int, Dict[str, object]] = {}
        self._optimizer = None
        self._step_fn = None

    # ------------------------------------------------------------- bind

    def bind(self, data_shapes, label_shapes=None, **_):
        import jax

        n_body = len(self._stages) - 2
        if self._mesh is None:
            self._mesh = make_mesh({self._axis: n_body})
        if self._mesh.shape[self._axis] != n_body:
            raise ValueError(
                "mesh axis %r has %d devices but there are %d body stages"
                % (self._axis, self._mesh.shape[self._axis], n_body))

        self._data_name, data_shape = data_shapes[0][0], data_shapes[0][1]
        self._label_name = label_shapes[0][0] if label_shapes else None
        label_shape = label_shapes[0][1] if label_shapes else None
        B = data_shape[0]
        if B % self._n_micro:
            raise ValueError("batch %d not divisible by %d microbatches"
                             % (B, self._n_micro))
        mb = B // self._n_micro
        self._batch = B
        mb_data = (mb,) + tuple(data_shape[1:])
        mb_label = (mb,) + tuple(label_shape[1:]) if label_shape else None

        # per-stage shape inference walks the wire through the stages
        self._stage_args: List[Dict[str, tuple]] = []
        self._stage_aux_shapes: List[Dict[str, tuple]] = []
        for i, sym in enumerate(self._stages):
            aux_names = sym.list_auxiliary_states()
            body_stage = 0 < i < len(self._stages) - 1
            if aux_names and not (body_stage and self._schedule == "1f1b"):
                raise MXNetError(
                    "auxiliary states (%s in stage %d) are only supported "
                    "in body stages under schedule='1f1b' (the adapter "
                    "and head run replicated on every device, where "
                    "per-microbatch running stats would diverge)"
                    % (aux_names, i))
            feed = {}
            if i == 0:
                feed[self._data_name] = mb_data
            else:
                feed["x"] = self._wire_shape
            if i == len(self._stages) - 1 and self._label_name and \
                    self._label_name in sym.list_arguments():
                feed[self._label_name] = mb_label
            arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**feed)
            args = {n: tuple(s) for n, s in
                    zip(sym.list_arguments(), arg_shapes)
                    if n not in feed}
            self._stage_args.append(args)
            self._stage_aux_shapes.append(
                {n: tuple(s) for n, s in zip(aux_names, aux_shapes)})
            if i < len(self._stages) - 1:
                self._wire_shape = tuple(out_shapes[0])
            else:
                self._out_shape = tuple(out_shapes[0])

        # body stages may use per-stage names (b1_*, b2_*, ...): matched
        # positionally in sorted-name order. Equal per-stage shapes ->
        # the stacked, param-sharded fast path (gpipe); unequal ->
        # heterogeneous mode (per-stage trees, replicated).
        body = self._stage_args[1:-1]
        canon = sorted(body[0])
        self._body_order = [sorted(b) for b in body]
        self._hetero = False
        for i, names in enumerate(self._body_order):
            shapes = [body[i][n] for n in names]
            want = [body[0][n] for n in canon]
            if len(shapes) != len(want) or shapes != want:
                self._hetero = True
        # aux states must line up too for the stacked layout
        baux = self._stage_aux_shapes[1:-1]
        self._aux_order = [sorted(a) for a in baux]
        for i, names in enumerate(self._aux_order):
            shapes = [baux[i][n] for n in names]
            want = [baux[0][n] for n in self._aux_order[0]]
            if len(shapes) != len(want) or shapes != want:
                self._hetero = True

        self._fns = [graph_function(s) for s in self._stages]
        self._bound = True
        return self

    # ----------------------------------------------------------- params

    def init_params(self, initializer=None, force_init=False):
        from .. import initializer as init_mod
        initializer = initializer or init_mod.Uniform(0.01)
        if self._params and not force_init:
            return
        for i, args in enumerate(self._stage_args):
            stage_params = {}
            for name, shape in args.items():
                arr = nd_mod.zeros(shape, dtype=np.float32)
                initializer(init_mod.InitDesc(name, {}), arr)
                stage_params[name] = np.asarray(arr.asnumpy())
            self._params[i] = stage_params
            stage_aux = {}
            for name, shape in self._stage_aux_shapes[i].items():
                arr = nd_mod.zeros(shape, dtype=np.float32)
                initializer(init_mod.InitDesc(name, {}), arr)
                stage_aux[name] = np.asarray(arr.asnumpy())
            self._aux[i] = stage_aux

    def get_params(self):
        """Per-stage parameter dicts, reflecting training: after
        init_optimizer the authoritative copies live on device
        (fit_step's donated jit updates them), so read those back."""
        if getattr(self, "_dev_params", None) is None:
            return {i: dict(p) for i, p in self._params.items()}
        n_stage = len(self._stages)
        out = {0: {k: np.asarray(v)
                   for k, v in self._dev_params["first"].items()}}
        body = self._dev_params["body"]
        if isinstance(body, tuple):        # heterogeneous (tuple) layout
            for i in range(1, n_stage - 1):
                out[i] = {n: np.asarray(v)
                          for n, v in body[i - 1].items()}
        else:                              # stacked layout
            canon = sorted(self._stage_args[1])
            for i in range(1, n_stage - 1):
                names = self._body_order[i - 1]
                out[i] = {n: np.asarray(body[c][i - 1])
                          for c, n in zip(canon, names)}
        out[n_stage - 1] = {k: np.asarray(v)
                            for k, v in self._dev_params["last"].items()}
        return out

    def get_aux(self):
        """Per-stage auxiliary states (1f1b schedule only)."""
        dev = getattr(self, "_dev_aux", None)
        if isinstance(dev, tuple):            # heterogeneous layout
            return {i + 1: {k: np.asarray(v) for k, v in t.items()}
                    for i, t in enumerate(dev)}
        if isinstance(dev, dict) and dev:     # stacked layout
            acanon = sorted(self._stage_aux_shapes[1])
            return {i + 1: {n: np.asarray(dev[c][i])
                            for c, n in zip(acanon, self._aux_order[i])}
                    for i in range(len(self._aux_order))}
        return {i: dict(a) for i, a in self._aux.items() if a}

    _LOSS_OPS = ("SoftmaxOutput", "MakeLoss", "LinearRegressionOutput",
                 "MAERegressionOutput", "LogisticRegressionOutput",
                 "SVMOutput")

    def _head_normalizes(self):
        """True when the head stage's loss ops normalize their gradient
        by row count per call (so per microbatch, not per batch):
        SoftmaxOutput/MakeLoss with normalization batch/valid (ops/nn.py
        _softmax_output_bwd). A head mixing normalized and unnormalized
        loss ops has no single 1/M correction — reject it."""
        from ..symbol.symbol import _topo_order
        normed, unnormed = [], []
        for node in _topo_order(self._stages[-1]._entries):
            if node.op is None or node.op.name not in self._LOSS_OPS:
                continue
            if node.attrs.get("normalization") in ("batch", "valid"):
                normed.append(node.name)
            else:
                unnormed.append(node.name)
        if normed and unnormed:
            raise MXNetError(
                "head stage mixes per-batch-normalized loss ops %s with "
                "unnormalized ones %s; the GPipe microbatch-accumulation "
                "correction (1/n_microbatches) cannot apply to both — "
                "use one normalization mode across the head's losses"
                % (normed, unnormed))
        return bool(normed)

    # -------------------------------------------------------- optimizer

    def init_optimizer(self, optimizer="sgd", optimizer_params=None):
        import jax
        import jax.numpy as jnp

        if not self._bound:
            raise MXNetError("bind before init_optimizer")
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params or {})
            # per-example gradient scaling, same convention as
            # Module.init_optimizer (module.py:345-351)
            optimizer_params.setdefault("rescale_grad", 1.0 / self._batch)
            optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer = optimizer

        fns = self._fns
        n_stage = len(self._stages)
        data_name, label_name = self._data_name, self._label_name
        mesh, axis, n_micro = self._mesh, self._axis, self._n_micro
        remat = self._remat
        # microbatch-accumulation invariance (see module docstring)
        acc_scale = 1.0 / n_micro if self._head_normalizes() else 1.0
        opt = self._optimizer
        tuple_mode = self._hetero

        # ---- stage closures over graph_function. The key rides as a
        # "__key__" leaf in the gpipe path (3-ary calls) and as an
        # explicit trailing argument in the 1f1b path.
        def first_fn(p, raw, *k):
            kk = k[0] if k else p["__key__"]
            feed = {kk2: v for kk2, v in p.items() if kk2 != "__key__"}
            feed[data_name] = raw[data_name]
            outs, _ = fns[0](feed, {}, kk, True)
            return outs[0]

        def last_fn(p, y, raw, *k):
            kk = k[0] if k else p["__key__"]
            feed = {kk2: v for kk2, v in p.items() if kk2 != "__key__"}
            feed["x"] = y
            if label_name is not None:
                feed[label_name] = raw[label_name]
            outs, _ = fns[n_stage - 1](feed, {}, kk, True)
            return outs[0]

        def body_fn_gpipe(i):
            def sfn(p, x):
                feed = {kk: v for kk, v in p.items() if kk != "__key__"}
                feed["x"] = x
                outs, _ = fns[i]({**feed}, {}, p["__key__"], True)
                return outs[0]
            return sfn

        def body_fn_1f1b(i):
            def sfn(p, a, x, kk):
                feed = dict(p)
                feed["x"] = x
                outs, new_aux = fns[i](feed, a, kk, True)
                return outs[0], new_aux
            return sfn

        def body_fn_1f1b_stacked(p, a, x, kk):
            """Single fn over stage-1's graph with stage-1 (canon) names;
            all body graphs agree structurally in the stacked case."""
            outs, new_aux = fns[1]({**p, "x": x}, a, kk, True)
            return outs[0], new_aux

        # ---- assemble device param pytrees
        if tuple_mode:
            body_trees = tuple(
                {n: jnp.asarray(self._params[i][n])
                 for n in self._stage_args[i]}
                for i in range(1, n_stage - 1))
            body_aux = tuple(
                {n: jnp.asarray(self._aux[i][n])
                 for n in self._stage_aux_shapes[i]}
                for i in range(1, n_stage - 1))
        else:
            canon = sorted(self._stage_args[1])
            acanon = sorted(self._stage_aux_shapes[1])
            per_stage, per_aux = [], []
            for i in range(1, n_stage - 1):
                names = self._body_order[i - 1]
                per_stage.append({c: jnp.asarray(self._params[i][n])
                                  for c, n in zip(canon, names)})
                per_aux.append({c: jnp.asarray(self._aux[i][n])
                                for c, n in zip(acanon,
                                                self._aux_order[i - 1])})
            body_trees = stack_stage_params(per_stage)
            body_aux = stack_stage_params(per_aux) if acanon else \
                ({} if self._schedule == "1f1b" else None)
        self._dev_params = {
            "first": {k: jnp.asarray(v)
                      for k, v in self._params[0].items()},
            "body": body_trees,
            "last": {k: jnp.asarray(v)
                     for k, v in self._params[n_stage - 1].items()},
        }
        self._dev_aux = body_aux if self._schedule == "1f1b" else None

        # ---- the jitted step
        if self._schedule == "1f1b":
            if tuple_mode:
                stage_fns = [body_fn_1f1b(i)
                             for i in range(1, n_stage - 1)]
            else:
                # homogeneous: single fn + stacked P(axis)-sharded params
                stage_fns = body_fn_1f1b_stacked

            def step(params, aux, states, inputs, key, lr, t):
                res = pipeline_1f1b(
                    stage_fns, params["body"], inputs, mesh=mesh,
                    axis=axis, first_fn=first_fn,
                    first_params=params["first"], last_fn=last_fn,
                    last_params=params["last"], key=key, stage_aux=aux)
                outs, grads, new_aux = res
                gtree = {"first": grads["first"],
                         "body": grads["stages"],
                         "last": grads["last"]}
                if acc_scale != 1.0:
                    gtree = jax.tree_util.tree_map(
                        lambda g: g * acc_scale, gtree)
                new_p, new_s = _apply_opt(params, gtree, states, lr, t)
                return outs, new_p, new_s, new_aux

            self._step_jit = jax.jit(step, donate_argnums=(0, 1, 2))
        else:
            if tuple_mode:
                stage_arg = [body_fn_gpipe(i)
                             for i in range(1, n_stage - 1)]
            else:
                stage_arg = body_fn_gpipe(1)

            def loss_like(params, inputs, key):
                # distinct key per stage (identically-built stages would
                # otherwise drop identical dropout coordinates); the
                # microbatch key is still shared under gpipe — a
                # documented limitation, lifted by schedule="1f1b"
                fp = dict(params["first"])
                fp["__key__"] = jax.random.fold_in(key, n_stage - 2)
                lp = dict(params["last"])
                lp["__key__"] = jax.random.fold_in(key, n_stage - 1)
                if tuple_mode:
                    sp = tuple(
                        dict(tr, __key__=jax.random.fold_in(key, i))
                        for i, tr in enumerate(params["body"]))
                else:
                    sp = dict(params["body"])
                    sp["__key__"] = jax.vmap(
                        lambda i: jax.random.fold_in(key, i))(
                        jnp.arange(n_stage - 2))
                outs = pipeline_apply(
                    stage_arg, sp, inputs, mesh=mesh, axis=axis,
                    first_fn=first_fn, first_params=fp,
                    last_fn=last_fn, last_params=lp, remat=remat)
                return jnp.sum(outs.astype(jnp.float32)), outs

            def step(params, states, inputs, key, lr, t):
                grads, outs = jax.grad(loss_like, has_aux=True)(
                    params, inputs, key)
                if acc_scale != 1.0:
                    grads = jax.tree_util.tree_map(
                        lambda g: g * acc_scale, grads)
                new_p, new_s = _apply_opt(params, grads, states, lr, t)
                return outs, new_p, new_s

            self._step_jit = jax.jit(step, donate_argnums=(0, 1))

        def _apply_opt(params, grads, states, lr, t):
            """One optimizer update per parameter leaf, deterministic
            leaf order across the {first, body, last} groups. Each
            parameter's optimizer state may itself be a subtree
            (momentum array, adam (m, v), or None) — flatten_up_to
            groups it per parameter."""
            flat_p, tdef = jax.tree_util.tree_flatten(params)
            flat_g = tdef.flatten_up_to(grads)
            flat_s = tdef.flatten_up_to(states)
            new_p, new_s = [], []
            for idx, (w, g, s) in enumerate(zip(flat_p, flat_g, flat_s)):
                w2, s2 = opt.raw_update(idx, w, g.astype(w.dtype), s,
                                        lr=lr, t=t)
                new_p.append(w2)
                new_s.append(s2)
            return (jax.tree_util.tree_unflatten(tdef, new_p),
                    jax.tree_util.tree_unflatten(tdef, new_s))

        # optimizer state per leaf (momentum etc.); SGD w/o momentum ->
        # None-shaped zeros so the state tree matches the param tree
        def state_for(w):
            s = opt.create_state(0, nd_mod.array(np.zeros(w.shape,
                                                          np.float32)))
            return jax.tree_util.tree_map(
                lambda x: jnp.zeros(w.shape, jnp.float32)
                if hasattr(x, "shape") else x, s,
                is_leaf=lambda x: x is None or hasattr(x, "shape"))

        self._dev_states = jax.tree_util.tree_map(
            state_for, self._dev_params,
            is_leaf=lambda x: hasattr(x, "shape"))
        self._t = 0

    # ------------------------------------------------------------- step

    def fit_step(self, data_batch):
        """One pipelined train step; returns the head outputs
        (n_microbatches, mb, ...)."""
        import jax
        import jax.numpy as jnp

        if self._optimizer is None:
            raise MXNetError("init_optimizer before fit_step")
        B = self._batch
        M = self._n_micro
        x = np.asarray(data_batch.data[0].asnumpy())
        inputs = {self._data_name:
                  jnp.asarray(x.reshape((M, B // M) + x.shape[1:]))}
        if self._label_name is not None:
            y = np.asarray(data_batch.label[0].asnumpy())
            inputs[self._label_name] = jnp.asarray(
                y.reshape((M, B // M) + y.shape[1:]))
        key = jax.random.PRNGKey(self._t)
        # Module's fused-step lr convention (module.py:530-537):
        # advance num_update and honor the lr scheduler
        self._t += 1
        self._optimizer.num_update = self._t
        if getattr(self._optimizer, "lr_scheduler", None) is not None:
            lr = self._optimizer.lr_scheduler(self._t)
        else:
            lr = self._optimizer.lr
        lr = jnp.asarray(lr, jnp.float32)
        t = jnp.asarray(self._t, jnp.int32)
        if self._schedule == "1f1b":
            outs, self._dev_params, self._dev_states, self._dev_aux = \
                self._step_jit(self._dev_params, self._dev_aux,
                               self._dev_states, inputs, key, lr, t)
        else:
            outs, self._dev_params, self._dev_states = self._step_jit(
                self._dev_params, self._dev_states, inputs, key, lr, t)
        return outs

    def fit(self, train_iter, num_epoch=1, eval_metric=None):
        """Minimal fit loop: fit_step per batch (metric optional)."""
        from .. import metric as metric_mod
        if eval_metric is not None and not hasattr(eval_metric, "update"):
            eval_metric = metric_mod.create(eval_metric)
        for epoch in range(num_epoch):
            train_iter.reset()
            for batch in train_iter:
                outs = self.fit_step(batch)
                if eval_metric is not None:
                    # (M,) + per-microbatch head shape -> flatten the
                    # microbatch axis into the leading row axis
                    flat = nd_mod.array(np.asarray(outs).reshape(
                        (-1,) + self._out_shape[1:]))
                    eval_metric.update(batch.label, [flat])
            if eval_metric is not None:
                self.logger.info("Epoch[%d] %s", epoch,
                                 eval_metric.get())
        return self

"""Module — binds a Symbol to devices and drives training.

Reference: ``python/mxnet/module/module.py`` — ``Module`` (line 39):
``bind:351`` creates a DataParallelExecutorGroup, ``init_optimizer:461``
decides update_on_kvstore, ``forward:556``/``backward:598``/``update:615``
drive the executors and the kvstore push/pull.

TPU design (SURVEY.md §2.21 + §7): the per-device executor group collapses
into ONE jitted program. ``context=[...]`` with more than one device builds a
``data``-axis mesh; inputs are batch-sharded, parameters replicated, and the
gradient all-reduce the reference routed through KVStore Comm
(src/kvstore/comm.h:73-380) is inserted by XLA as a psum over ICI. The fit
hot loop uses a fused forward+backward+optimizer-update program with donated
buffers so weights never leave HBM.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import Context, cpu
from .. import ndarray as nd
from .. import optimizer as opt
from ..executor import Executor, graph_function
from ..initializer import InitDesc
from ..model import _create_kvstore, load_checkpoint, save_checkpoint
from .. import config as _config
from .. import _fused
from .. import profiler as _profiler
from ..obs import compiles as _obs_compiles
from ..obs import mfu as _obs_mfu
from .base_module import BaseModule, _check_input_names
from ..io.io import DataDesc

__all__ = ["Module"]

# one compiled executable per (shapes, dtypes) signature, shared by every
# checkpoint snapshot of the same model — no donation, so the inputs (the
# live training buffers) stay valid and the outputs are owned copies
_snapshot_copy = jax.jit(lambda xs: [jnp.copy(x) for x in xs])


def _accum_loss_scale(symbol, accum: int) -> float:
    """Gradient rescale that makes an N-microbatch accumulated step
    match the unaccumulated full-batch step.

    Loss-head backward contract (ops/nn.py): ``normalization='null'``
    (and the regression/SVM heads, and plain outputs driven by
    ones-cotangents) produce **per-sample** gradients — summing the N
    microbatch gradients IS the full-batch gradient, scale 1.
    ``normalization='batch'`` divides by the (micro)batch size, so the
    accumulated sum is N x the full-batch mean — scale 1/N (equal-sized
    microbatches make the mean-of-means exact).
    ``normalization='valid'`` divides by a data-dependent count per
    microbatch; no uniform rescale reproduces the full-batch step, so
    it is rejected, as is a mix of batch-mean and per-sample heads."""
    kinds = set()
    for node, _ in symbol._entries:
        if node.is_variable:
            kinds.add("sample")
            continue
        norm = node.attrs.get("normalization")
        if norm == "valid":
            raise MXNetError(
                "grad_accum: %s head %r uses normalization='valid' "
                "(a per-batch valid count cannot be replayed per "
                "microbatch) — use 'batch' or 'null'"
                % (node.op.name, node.name))
        kinds.add("batch" if norm == "batch" else "sample")
    if kinds == {"batch"}:
        return 1.0 / accum
    if "batch" in kinds:
        raise MXNetError(
            "grad_accum: loss heads mix batch-mean and per-sample/sum "
            "normalization; the accumulated gradient cannot be rescaled "
            "consistently — align the heads' normalization")
    return 1.0


class Module(BaseModule):
    """A bound Symbol + parameters + optimizer (reference: module.py:39)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, mesh_shape=None, param_shardings=None,
                 layout=None):
        """``mesh_shape``/``param_shardings`` are the tensor-parallel
        surface (SURVEY §2.21): ``mesh_shape={"data": 2, "model": 4}``
        lays the context list out as a 2D mesh, and ``param_shardings``
        maps parameter names (exact or regex) to ``parallel.P`` partition
        specs over those axes — e.g. ``{"fc1_weight": P("model", None)}``
        column-shards fc1. The batch stays sharded over ``data``; XLA
        partitions the matmuls and inserts the tensor-parallel collectives
        from the operand shardings (GSPMD), so the same fused train step
        serves dp, tp, and dp x tp without code changes.

        ``layout`` (docs/architecture/parallelism.md) is the unified
        entry point above both: a ``parallel.SpecLayout`` builds the
        canonical ``data x fsdp x tp`` mesh, shards every batch over
        ``(data, fsdp)``, and resolves each parameter's spec through the
        layout's overrides + name heuristic — parameters AND their
        optimizer states shard over ``fsdp`` (ZeRO-style), with explicit
        ``param_shardings`` still winning per name. The same layout
        object drives checkpoint reshard-on-load
        (``read_checkpoint(layout=...)``), so save/restore can never
        resolve differently than the bind."""
        super().__init__(logger=logger)
        if context is None:
            context = cpu()
        if isinstance(context, Context):
            context = [context]
        self._context: List[Context] = list(context)
        self._mesh_shape = dict(mesh_shape) if mesh_shape else None
        self._param_shardings = dict(param_shardings) \
            if param_shardings else None
        self._layout = None
        self._batch_sharding = None
        if layout is not None:
            self.set_layout(layout)
        # work_load_list existed to weight uneven GPUs
        # (executor_group.py:99); a TPU mesh is homogeneous, accepted and
        # ignored for API compatibility.
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []
        state_names = list(state_names) if state_names is not None else []
        fixed_param_names = list(fixed_param_names) if fixed_param_names \
            is not None else []
        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, state_names, "state", True)
        _check_input_names(symbol, fixed_param_names, "fixed_param", True)

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names + state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._fixed_param_names = fixed_param_names
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = [n for n in label_names if n in arg_names]
        self._state_names = state_names
        self._output_names = symbol.list_outputs()

        self._arg_params: Optional[Dict[str, nd.NDArray]] = None
        self._aux_params: Optional[Dict[str, nd.NDArray]] = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._fused_updater = None
        self._preload_opt_states = None

        self._exec: Optional[Executor] = None
        self._grad_accum = 1
        self._data_shapes = None
        self._label_shapes = None
        self._grad_req = None
        self._mesh = None
        self._fused = None          # jitted fused train step
        self._fused_out = None      # outputs of the last fused step
        self._fused_states = None   # optimizer-state pytree for fused path
        self._fused_num_update = 0

        # obs utilization accounting (docs/architecture/observability.md):
        # per-step cost is two attribute writes + one perf_counter read;
        # rates/MFU are computed lazily by mx.obs.report()
        self._obs_steps = 0
        self._obs_t0 = None
        self._obs_baseline = None
        self._obs_flops_per_step = None
        self._obs_label = "module"
        self._obs_sig = None

    # ------------------------------------------------------------- loading
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create a Module from a saved checkpoint (reference:
        module.py:114)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """(reference: module.py:152)."""
        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, *self.get_params())
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    # ------------------------------------------------------------- shapes
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec.outputs
        return list(zip(self._output_names, [o.shape for o in outs]))

    # ------------------------------------------------------------- params
    def get_params(self):
        """(reference: module.py get_params)."""
        assert self.binded and self.params_initialized
        self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def _sync_params_from_devices(self):
        """Copy bound executor values back into _arg_params (reference:
        module.py _sync_params_from_devices). One jax.Array is the single
        source of truth here, so 'sync' is a dict refresh."""
        if not self.binded or not self.params_initialized:
            return
        if self._exec is not None and self._params_dirty:
            for n in self._param_names:
                self._arg_params[n] = self._exec.arg_dict[n]
            for n in self._aux_names:
                self._aux_params[n] = self._exec.aux_dict[n]
            self._params_dirty = False

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """(reference: module.py init_params — attr-driven InitDesc
        dispatch)."""
        assert self.binded, "call bind before initializing the parameters"
        if self.params_initialized and not force_init:
            return
        attrs = self.symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    if cache_arr.shape != arr.shape:
                        raise MXNetError(
                            "shape mismatch for %s: %s vs %s"
                            % (name, cache_arr.shape, arr.shape))
                    arr[:] = cache_arr
            elif cache is not None and not allow_missing:
                raise RuntimeError("%s is not presented" % name)
            elif initializer is not None:
                initializer(InitDesc(name, attrs.get(name, None)), arr)

        for name in self._param_names:
            _impl(name, self._exec.arg_dict[name], arg_params)
        for name in self._aux_names:
            _impl(name, self._exec.aux_dict[name], aux_params)

        self._arg_params = {n: self._exec.arg_dict[n]
                            for n in self._param_names}
        self._aux_params = {n: self._exec.aux_dict[n]
                            for n in self._aux_names}
        self.params_initialized = True
        self._params_dirty = False
        if self._mesh is not None:
            self._replicate_params()

    def set_layout(self, layout) -> None:
        """Install the unified ``parallel.SpecLayout`` (the ROADMAP
        item-1 entry point; ``fit(layout=...)`` routes here): the bind
        builds the canonical ``data x fsdp x tp`` mesh from it, batches
        shard over ``(data, fsdp)``, and every parameter + optimizer
        state resolves its spec through the layout (explicit
        ``param_shardings`` still win per name). Must be called before
        bind — an already-bound module would need force_rebind to re-lay
        its buffers out."""
        if layout is not None and not hasattr(layout, "spec_for"):
            raise MXNetError(
                "set_layout expects a parallel.SpecLayout (got %r)"
                % (type(layout).__name__,))
        if self.binded:
            if layout == self._layout:
                return          # idempotent re-fit with the same layout
            raise MXNetError(
                "set_layout must run before bind (rebind with "
                "force_rebind=True to change an existing module's "
                "layout)")
        if layout is not None and self._mesh_shape is not None:
            raise MXNetError(
                "layout and mesh_shape are mutually exclusive — the "
                "layout IS the mesh shape (axes %r)" % (layout.axes(),))
        self._layout = layout

    def _sharding_for(self, name):
        """Resolve a parameter's NamedSharding: an exact or regex match in
        param_shardings wins (tensor parallel), then the bound
        SpecLayout's overrides + name heuristic (FSDP/tp), else
        replicated (data parallel). Delegates to the canonical resolver
        shared with checkpoint reshard-on-load
        (parallel.mesh.resolve_layout_spec)."""
        from jax.sharding import NamedSharding
        from ..parallel.mesh import replicated_sharding, resolve_layout_spec
        if self._param_shardings:
            spec = resolve_layout_spec(self._param_shardings, name)
            if spec is not None:
                return NamedSharding(self._mesh, spec)
        if self._layout is not None:
            arr = self._exec.arg_dict.get(name) if self._exec is not None \
                else None
            if arr is None and self._exec is not None:
                arr = self._exec.aux_dict.get(name)
            spec = resolve_layout_spec(
                self._layout, name,
                shape=tuple(arr.shape) if arr is not None else None,
                dtype=arr.dtype if arr is not None else None)
            if spec is not None:
                return NamedSharding(self._mesh, spec)
        return replicated_sharding(self._mesh)

    def _replicate_params(self):
        """Place parameters on the mesh: replicated over ``data``, and
        partitioned per param_shardings over ``model`` (replaces per-device
        param copies in executor_group.py + kvstore broadcast). Spec
        divisibility is validated per parameter first, so restoring a
        checkpoint onto a mesh its layout cannot divide fails naming the
        offending array (the elastic reshard-on-load contract) instead
        of surfacing as an XLA sharding error."""
        from ..parallel.mesh import validate_spec
        for d in (self._exec.arg_dict, self._exec.aux_dict):
            for name, arr in d.items():
                sharding = self._sharding_for(name)
                try:
                    validate_spec(self._mesh, sharding.spec,
                                  tuple(arr.shape), name=name)
                except ValueError as exc:
                    raise MXNetError("cannot lay out parameters on the "
                                     "bound mesh: %s" % exc) from None
                arr._data = jax.device_put(arr._data, sharding)

    # ------------------------------------------------------------- binding
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """(reference: module.py:351). Shapes may be (name, shape) tuples or
        DataDesc."""
        if force_rebind:
            self._exec = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        self._data_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                             for x in data_shapes]
        self._label_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                              for x in label_shapes] if label_shapes else []

        shape_hints = {d.name: d.shape for d in self._data_shapes}
        shape_hints.update({d.name: d.shape for d in self._label_shapes
                            if d.name in self._symbol.list_arguments()})

        mesh_shape = self._mesh_shape
        if mesh_shape is None and self._layout is not None:
            # the unified layout IS the mesh shape: always all three
            # canonical axes (size-1 axes cost nothing and keep every
            # spec valid on every shape)
            mesh_shape = self._layout.axes()
        if mesh_shape is not None:
            from ..parallel.mesh import make_mesh
            if len(self._context) > 1:
                want = int(np.prod([s for s in mesh_shape.values()
                                    if s != -1]))
                if -1 not in mesh_shape.values() \
                        and want != len(self._context):
                    raise ValueError(
                        "mesh_shape %r uses %d devices but %d contexts "
                        "were given — they must match (use -1 to absorb "
                        "the rest)" % (mesh_shape, want,
                                       len(self._context)))
            self._mesh = make_mesh(mesh_shape,
                                   contexts=self._context
                                   if len(self._context) > 1 else None)
        elif len(self._context) > 1:
            from ..parallel.mesh import data_parallel_mesh
            self._mesh = data_parallel_mesh(self._context)
        else:
            self._mesh = None

        self._batch_sharding = None
        if self._layout is not None and self._mesh is not None:
            # one NamedSharding built per bind (the placer is hot), and
            # the batch divisibility checked HERE so an indivisible
            # batch fails naming the input, not as an XLA error later
            from jax.sharding import NamedSharding
            from ..parallel.mesh import validate_spec
            spec = self._layout.batch_spec()
            for d in self._data_shapes + self._label_shapes:
                if not d.shape:
                    continue
                try:
                    validate_spec(self._mesh, spec, tuple(d.shape),
                                  name=d.name)
                except ValueError as exc:
                    raise MXNetError(
                        "layout: cannot shard the batch over (%s, %s): %s"
                        % (self._layout.data_axis, self._layout.fsdp_axis,
                           exc)) from None
            self._batch_sharding = NamedSharding(self._mesh, spec)

        req = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names:
                req[n] = "write" if inputs_need_grad else "null"
            elif n in self._label_names or n in self._state_names:
                req[n] = "null"
            elif n in self._fixed_param_names:
                req[n] = "null"
            else:
                req[n] = grad_req if for_training else "null"
        self._grad_req = req

        type_dict = {d.name: d.dtype for d in self._data_shapes +
                     self._label_shapes}
        self._exec = self._symbol.simple_bind(
            self._context[0], grad_req=req, type_dict=type_dict,
            **shape_hints)
        self.binded = True

        if self.params_initialized:
            # params were set before bind (Module.load / set_params on an
            # unbound module): push them into the fresh executor (reference:
            # module.py:351 bind → exec_group.set_params)
            self.init_params(arg_params=self._arg_params,
                             aux_params=self._aux_params,
                             allow_missing=False, force_init=True)

        if shared_module is not None and shared_module.params_initialized:
            self.init_params(arg_params=shared_module._arg_params,
                             aux_params=shared_module._aux_params,
                             allow_missing=False, force_init=True)

    # -------------------------------------------------------------- analysis
    def analyze(self, input_shapes=None, input_dtypes=None,
                sharding=False, collectives=False):
        """Run the static analyzer (``mxnet_tpu.analysis``) over this
        module's symbol: graph passes plus the memory passes (remat
        opportunities, HBM budget). Bound modules analyze with their
        actual bound shapes; unbound ones need ``input_shapes``.

        ``sharding=True`` additionally runs the sharding/communication
        audit on a mesh-bound module (spec validity, FSDP opportunities,
        ambiguous regex layering) — with ``collectives=True`` it also
        compiles the bound forward against its shardings and walks the
        partitioned HLO for collectives (``Report.extras["comm"]``;
        compiles one executable, so it is opt-in).

        Returns an ``analysis.Report`` (lazy import — never loaded
        unless called)."""
        from ..analysis import analyze_symbol
        shapes = {k: tuple(v) for k, v in (input_shapes or {}).items()}
        if not shapes and self.binded:
            shapes = {n: tuple(a.shape)
                      for n, a in self._exec.arg_dict.items()}
            shapes.update({n: tuple(a.shape)
                           for n, a in self._exec.aux_dict.items()})
        report = analyze_symbol(self._symbol, input_shapes=shapes or None,
                                input_dtypes=input_dtypes,
                                context="module",
                                grad_accum=getattr(self, "_grad_accum", 1),
                                batch_inputs=list(self._data_names)
                                + list(self._label_names))
        if sharding and self.binded and self._mesh is not None:
            from ..analysis import analyze_module_sharding
            report.extend(analyze_module_sharding(
                self, collectives=collectives))
        return report

    # ------------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """(reference: module.py:461 — builds kvstore, decides
        update_on_kvstore, pickles the optimizer to dist servers)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return

        arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
        kvstore, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), arg_params)

        # all data inputs share ONE batch size (reference:
        # executor_group.decide_slices asserts this; never summed)
        batch_sizes = {d.shape[0] for d in self._data_shapes if d.shape}
        if len(batch_sizes) > 1:
            raise MXNetError("data inputs disagree on batch size: %s"
                             % [(d.name, d.shape) for d in self._data_shapes])
        batch_size = batch_sizes.pop() if batch_sizes else 1
        if kvstore and "dist" in kvstore.type and "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but "
                    "rescale_grad is not normalized to 1.0/batch_size/"
                    "num_workers (%s vs. %s).",
                    optimizer.rescale_grad, rescale_grad)

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        optimizer.set_lr_mult({})
        optimizer.set_wd_mult({})

        if kvstore:
            # init kvstore entries; with update_on_kvstore the optimizer runs
            # inside the store (reference: model.py:106)
            for idx, name in enumerate(self._param_names):
                kvstore.init(idx, self._arg_params[name])
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
            self._fused_updater = _fused.FusedUpdater(self._updater)

        self.optimizer_initialized = True
        self._build_fused_step()

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        """(reference: module.py borrow_optimizer — bucketing support)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self._fused_updater = shared_module._fused_updater
        self.optimizer_initialized = True
        self._build_fused_step()

    def save_optimizer_states(self, fname):
        """(reference: module.py:761). With the fused step active, its state
        pytree is the authoritative optimizer state."""
        assert self.optimizer_initialized
        import pickle
        from ..checkpoint.atomic import atomic_open
        if self._fused is not None and self._fused_states is not None:
            states = jax.tree_util.tree_map(np.asarray, self._fused_states)
            with atomic_open(fname, "wb") as fout:
                pickle.dump({"fused": states,
                             "num_update": self._fused_num_update}, fout)
        elif self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname)
        else:
            with atomic_open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        """(reference: module.py load_optimizer_states)."""
        assert self.optimizer_initialized
        import pickle
        with open(fname, "rb") as fin:
            blob = fin.read()
        try:
            payload = pickle.loads(blob)
        except Exception:
            payload = None
        if isinstance(payload, dict) and "fused" in payload \
                and self._fused is not None:
            # commit each leaf onto its parameter's sharding — an
            # uncommitted jnp.asarray would lower the fused step under a
            # new key (one spurious recompile on the next fit step)
            def _place_state(n, s):
                bound = self._exec.arg_dict.get(n)

                def _leaf(x):
                    if x is None:
                        return None
                    x = jnp.asarray(x)
                    return x if bound is None else \
                        jax.device_put(x, bound.data.sharding)

                return jax.tree_util.tree_map(_leaf, s,
                                              is_leaf=lambda x: x is None)

            self._fused_states = {n: _place_state(n, s)
                                  for n, s in payload["fused"].items()}
            self._fused_num_update = payload["num_update"]
            self._optimizer.num_update = payload["num_update"]
        elif self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
        else:
            self._updater.set_states(blob)

    # ---------------------------------------------------------- checkpointing
    def _checkpoint_snapshot(self):
        """Capture everything exact resume needs as ``(tensors, meta)`` for
        ``mx.checkpoint`` (docs/architecture/checkpoint.md): parameters,
        aux states, the optimizer-state tree (fused pytree or the eager
        ``Updater`` dict), update counts, and both PRNG chains.

        The capture is the CHEAP phase of the CheckFreq split: one
        ``jnp.copy`` per array — a device-side dispatch, not a host
        transfer — protects each buffer before the next fused step
        donates and invalidates it (the fused jit donates params, states,
        and aux on EVERY backend, CPU included). The device->host fetch,
        checksums, and fsync all happen later on the writer thread. The
        caller must be at a step boundary with the in-flight window
        drained (``fit`` is).
        """
        assert self.binded and self.params_initialized
        from ..checkpoint.manager import key_to_array, tree_encode
        from ..checkpoint.format import CheckpointError
        ex = self._exec

        def grab(v):
            return v.data if isinstance(v, nd.NDArray) else v

        tensors = {}
        for n in self._param_names:
            tensors["arg:" + n] = grab(ex.arg_dict[n])
        for n in self._aux_names:
            tensors["aux:" + n] = grab(ex.aux_dict[n])
        meta = {"param_names": list(self._param_names),
                "aux_names": list(self._aux_names)}

        step = 0
        if self.optimizer_initialized:
            if self._fused is not None and self._fused_states is not None:
                structure = {
                    n: tree_encode("opt:%s" % n, s, tensors, grab)
                    for n, s in self._fused_states.items()}
                step = int(self._fused_num_update)
                meta["optimizer"] = {"kind": "fused",
                                     "structure": structure,
                                     "num_update": step}
            elif self._updater is not None:
                structure = {
                    str(idx): tree_encode("upd:%s" % idx, s, tensors, grab)
                    for idx, s in self._updater.states.items()}
                step = int(self._optimizer.num_update)
                meta["optimizer"] = {
                    "kind": "updater", "structure": structure,
                    "num_update": step,
                    "index_update_count": {
                        str(k): int(v) for k, v in
                        self._optimizer._index_update_count.items()}}
            elif self._update_on_kvstore and self._kvstore is not None \
                    and getattr(self._kvstore, "_updater_obj",
                                None) is not None:
                # SPMD dist kvstore: there is no server process — every
                # rank holds the SAME updater/optimizer state locally
                # (set_optimizer constructs it per process), so the
                # snapshot is as local as the eager-updater case. This is
                # what lets a multi-host pod checkpoint/resume through
                # the ordinary fit(checkpoint=..., resume_from=...) path.
                upd = self._kvstore._updater_obj
                structure = {
                    str(idx): tree_encode("upd:%s" % idx, s, tensors,
                                          grab)
                    for idx, s in upd.states.items()}
                step = int(upd.optimizer.num_update)
                meta["optimizer"] = {
                    "kind": "kvstore", "structure": structure,
                    "num_update": step,
                    "index_update_count": {
                        str(k): int(v) for k, v in
                        upd.optimizer._index_update_count.items()}}
            else:
                raise CheckpointError(
                    "optimizer state lives on the kvstore "
                    "(update_on_kvstore) and the store exposes no local "
                    "updater; mx.checkpoint cannot snapshot it — use "
                    "save_optimizer_states / the legacy "
                    "module_checkpoint callback instead")
        meta["step"] = step

        tensors["rng:executor_key"] = key_to_array(ex._base_key)
        meta["executor_step"] = int(ex._step)
        from .. import random as _random
        tensors["rng:global_key"] = key_to_array(_random.current_key())

        # mesh provenance for elastic resume: a restore onto a DIFFERENT
        # mesh is legitimate (reshard-on-load) but worth counting/logging
        if self._mesh is not None:
            from ..parallel.mesh import axis_sizes
            meta["mesh"] = axis_sizes(self._mesh)
        meta["world_size"] = int(self._mesh.devices.size) \
            if self._mesh is not None else 1
        from ..checkpoint.format import pod_info
        pod_rank, pod_world = pod_info()
        if pod_world > 1:
            # multi-host provenance: a resume at a different pod world
            # is the elastic reshard path (counted at restore)
            meta["pod"] = {"process_index": pod_rank,
                           "world_size": pod_world}

        # protect every captured device buffer in ONE jitted copy program
        # (a single dispatch instead of ~2 per-op milliseconds per array
        # — measurably the difference between ~10% and ~40% of the write
        # time on the bench); output buffers are fresh, so the next fused
        # step is free to donate the originals
        live = {k: v for k, v in tensors.items()
                if isinstance(v, jax.Array)}
        if live:
            copies = _snapshot_copy(list(live.values()))
            tensors.update(zip(live.keys(), copies))
        return tensors, meta

    def _checkpoint_restore(self, ckpt):
        """Replay a :class:`mx.checkpoint.Checkpoint`'s optimizer + RNG
        state onto this bound, optimizer-initialized module (parameters
        are restored separately through ``init_params`` — ``fit`` wires
        both). After this, the next fused step continues the interrupted
        run bit-identically: same optimizer-state bytes, same update
        count (so LR schedules resume mid-curve), same dropout key chain.
        """
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        from ..checkpoint.manager import array_to_key, tree_decode
        from ..checkpoint.format import CheckpointCorrupt
        tensors = ckpt.tensors

        # elastic resume accounting: restoring onto a different mesh /
        # world size than the save is the reshard-on-load path — the
        # host tensors were reassembled from the recorded index windows
        # and init_params/_replicate_params re-lay them out per THIS
        # module's mesh and param_shardings
        from ..parallel.mesh import axis_sizes
        saved_mesh = ckpt.meta.get("mesh")
        saved_world = ckpt.meta.get("world_size")
        cur_mesh = axis_sizes(self._mesh) if self._mesh is not None \
            else None
        cur_world = int(self._mesh.devices.size) \
            if self._mesh is not None else 1
        resharded = saved_world is not None and \
            (saved_mesh, int(saved_world)) != (cur_mesh, cur_world)
        if resharded:
            self.logger.info(
                "resume: resharding checkpoint saved on mesh %s "
                "(world %s) onto mesh %s (world %d)",
                saved_mesh, saved_world, cur_mesh, cur_world)
        from ..checkpoint.format import pod_info
        saved_pod = int((ckpt.meta.get("pod") or {}).get("world_size", 1))
        cur_pod = pod_info()[1]
        if saved_pod != cur_pod:
            # host death / pod growth: the surviving world resumes the
            # dead world's checkpoint (reassembled from its per-host
            # index windows)
            self.logger.info(
                "resume: checkpoint saved by a %d-host pod restoring "
                "onto a %d-host pod", saved_pod, cur_pod)
        if resharded or saved_pod != cur_pod:
            # ONE reshard event per resume, however many dimensions
            # (device mesh, pod world) changed at once
            _profiler.incr_counter("elastic_reshard")
        from .base_module import _blackbox
        _bb = _blackbox()
        if _bb is not None:
            # the post-mortem's "where did the survivors pick up":
            # which checkpoint, and whether the restore resharded
            _bb.record("resume", os.path.basename(ckpt.path),
                       step=ckpt.step, resharded=bool(resharded),
                       saved_world=saved_world, cur_world=cur_world,
                       saved_pod=saved_pod, cur_pod=cur_pod)
        opt_meta = ckpt.meta.get("optimizer") or {}
        kind = opt_meta.get("kind")
        if kind == "fused":
            if self._fused is None:
                raise CheckpointCorrupt(
                    "%s holds a fused optimizer-state tree but this "
                    "module has no fused step (kvstore/custom-updater "
                    "binding)" % ckpt.path)
            structure = opt_meta["structure"]
            if set(structure) != set(self._fused_states or {}):
                raise CheckpointCorrupt(
                    "%s: optimizer-state params %s do not match the "
                    "bound module's %s"
                    % (ckpt.path, sorted(structure),
                       sorted(self._fused_states or {})))

            # commit each leaf onto the sharding make_states placed the
            # fresh state on (= the parameter's) — an uncommitted
            # jnp.asarray would re-lower the fused step AND break
            # donation on the first resumed step
            def _restore_state(n, s):
                bound = self._exec.arg_dict.get(n)

                def leaf(x):
                    x = jnp.asarray(x)
                    return x if bound is None else \
                        jax.device_put(x, bound.data.sharding)

                return tree_decode("opt:%s" % n, s, tensors, leaf)

            self._fused_states = {n: _restore_state(n, s)
                                  for n, s in structure.items()}
            self._fused_num_update = int(opt_meta["num_update"])
            self._optimizer.num_update = self._fused_num_update
        elif kind == "updater":
            if self._updater is None:
                raise CheckpointCorrupt(
                    "%s holds eager Updater state but this module has "
                    "no local updater" % ckpt.path)
            states = {}
            for sidx, s in opt_meta["structure"].items():
                idx = int(sidx) if sidx.lstrip("-").isdigit() else sidx
                # preserve the saved dtype (nd.array defaults to f32):
                # an f16 momentum buffer resuming as f32 would make the
                # resumed updates compute at a different precision
                states[idx] = tree_decode(
                    "upd:%s" % sidx, s, tensors,
                    lambda x: nd.array(np.asarray(x),
                                       dtype=np.asarray(x).dtype))
            self._updater.states = states
            self._optimizer.num_update = int(opt_meta["num_update"])
            self._optimizer._index_update_count.update(
                {int(k): int(v) for k, v in
                 opt_meta.get("index_update_count", {}).items()})
            self._fused_num_update = self._optimizer.num_update
        elif kind == "kvstore":
            upd = getattr(self._kvstore, "_updater_obj", None) \
                if self._kvstore is not None else None
            if upd is None:
                raise CheckpointCorrupt(
                    "%s holds kvstore updater state but this module is "
                    "not bound to a kvstore with a local updater "
                    "(resume with the same kvstore= as the save)"
                    % ckpt.path)
            states = {}
            for sidx, s in opt_meta["structure"].items():
                idx = int(sidx) if sidx.lstrip("-").isdigit() else sidx
                states[idx] = tree_decode(
                    "upd:%s" % sidx, s, tensors,
                    lambda x: nd.array(np.asarray(x),
                                       dtype=np.asarray(x).dtype))
            upd.states.update(states)
            upd.optimizer.num_update = int(opt_meta["num_update"])
            upd.optimizer._index_update_count.update(
                {int(k): int(v) for k, v in
                 opt_meta.get("index_update_count", {}).items()})
            # the kvstore weight replicas need no replay: init_optimizer
            # already ran kvstore.init with the RESTORED params (fit
            # restores params before the optimizer), and every rank
            # restored the same checkpoint

        raw = tensors.get("rng:executor_key")
        if raw is not None:
            self._exec._base_key = array_to_key(raw,
                                                like=self._exec._base_key)
        es = ckpt.meta.get("executor_step")
        if es is not None:
            self._exec._step = int(es)

    # ------------------------------------------------------------- fused fit
    def _build_fused_step(self):
        """Compile the fit hot loop: forward + backward + optimizer update as
        ONE donated-buffer XLA program (SURVEY.md §7 'Hard parts').

        The per-step python work reduces to: place the batch, call the
        compiled function, swap the new param/state arrays in. With a mesh
        bound, inputs arrive batch-sharded and GSPMD turns the parameter
        gradients into psum-reduced replicated arrays — the collective the
        reference scheduled manually in kvstore Comm.
        """
        if self._updater is None and not self._update_on_kvstore:
            self._fused = None
            self._check_accum_needs_fused()
            return
        if self._update_on_kvstore and self._kvstore is not None \
                and "dist" in self._kvstore.type:
            self._fused = None  # real parameter-server path: not fusable
            self._check_accum_needs_fused()
            return

        optimizer = self._optimizer
        fn = self._exec._fn
        input_names = set(self._data_names) | set(self._label_names) \
            | set(self._state_names)
        # only grad-bearing params are differentiated + updated; fixed
        # params (grad_req null, reference fixed_param_names) ride along as
        # constants exactly like the eager update() path skips them
        param_names = [n for n in self._param_names
                       if self._grad_req.get(n, "null") != "null"]
        frozen = [n for n in self._symbol.list_arguments()
                  if n not in input_names and n not in param_names]
        name2idx = {n: i for i, n in enumerate(self._param_names)}

        # optimizer states are created eagerly (concrete zeros) and then
        # threaded through the jitted step as a pytree; each leaf is
        # committed onto its parameter's sharding — a fresh uncommitted
        # zeros array lowers under a different key than the committed
        # array the jit returns, which costs one spurious recompile (and
        # an unusable donation) on step 2
        def make_states():
            states = {}
            for n in param_names:
                s = optimizer.create_state(name2idx[n],
                                           self._exec.arg_dict[n])
                sharding = self._exec.arg_dict[n].data.sharding

                def _place(x, _sh=sharding):
                    if x is None:
                        return None
                    x = x.data if isinstance(x, nd.NDArray) else x
                    return jax.device_put(x, _sh)

                states[n] = jax.tree_util.tree_map(
                    _place, s,
                    is_leaf=lambda x: isinstance(x, nd.NDArray) or x is None)
            return states

        from .. import config as _config
        # ---- applied rematerialization (MXNET_TPU_REMAT; legacy alias
        # MXNET_EXEC_ENABLE_REMAT). With a scan plan bound, the executor
        # already wrapped each repeated block — exactly the granularity
        # the remat-opportunity suggestion prescribes — so only the
        # plan-less flat trace is wrapped here (whole-forward form).
        # Historical caveat (tools/perf/doc_evidence.py, note_memory.md):
        # on dense-attention transformers the flat save-policy form cuts
        # little (the T^2 score tensors must exist during the backward
        # recompute anyway); the per-block form over a scan plan is the
        # one that recovers residual-stream activations.
        remat_policy = None
        remat_name = getattr(self._exec, "_remat_name", "off")
        if remat_name == "off" and (
                _config.get("MXNET_TPU_REMAT") != "off"
                or _config.get("MXNET_EXEC_ENABLE_REMAT")):
            # the executor resolved the same whole-forward policy for
            # its non-fused fwd_bwd path already — reuse it (one
            # analysis run per bind, one remat_applied count)
            remat_policy = getattr(self._exec, "_fwd_bwd_remat", None)
            if remat_policy is not None:
                remat_name = getattr(self._exec, "_fwd_bwd_remat_name",
                                     "auto")
            else:
                from .. import remat as _remat
                shapes = {n: tuple(a.shape)
                          for n, a in self._exec.arg_dict.items()}
                shapes.update({n: tuple(a.shape)
                               for n, a in self._exec.aux_dict.items()})
                dts = {n: a.dtype for n, a in self._exec.arg_dict.items()}
                # aux dtypes too: BatchNorm running stats must price at
                # their real width in the remat ranking (the PR 8 rule)
                dts.update({n: a.dtype
                            for n, a in self._exec.aux_dict.items()})
                remat_policy, remat_name = _remat.resolve_policy(
                    self._symbol, input_shapes=shapes, input_dtypes=dts)
                if remat_policy is not None:
                    _profiler.incr_counter("remat_applied")
        self._remat_name = remat_name

        # ---- microbatch gradient accumulation (fit(grad_accum=N) /
        # set_grad_accum): the bound batch is split into N equal
        # microbatches driven through ONE lax.scan inside the step, so
        # only one microbatch's activations are ever live — batch sizes
        # that saturate the chip fit in HBM at N× smaller activation
        # high-water. Accumulated gradients are rescaled so the update
        # matches the unaccumulated full-batch step exactly (see
        # _accum_loss_scale for the loss-normalization contract).
        accum = max(1, int(getattr(self, "_grad_accum", 1) or 1))
        accum_scale = 1.0
        if accum > 1:
            for d in (self._data_shapes or []) + (self._label_shapes or []):
                if d.shape and d.shape[0] % accum:
                    raise MXNetError(
                        "grad_accum=%d does not divide the %r batch "
                        "dimension %d" % (accum, d.name, d.shape[0]))
            accum_scale = _accum_loss_scale(self._symbol, accum)
            _profiler.set_gauge("grad_accum", accum)

        # ---- grouped optimizer update over scan var-lists (the PR 9
        # close-out lever): with a scan plan bound, the forward already
        # traces ONE block whatever the depth — but the optimizer update
        # still traced L per-layer copies of itself (the residual O(L)
        # program eqns). Each verified per-layer parameter family
        # (scan_plan.var_lists) updates as ONE vmapped raw_update over
        # the stacked (L, ...) arrays instead: the update body traces
        # once per family. Families whose members resolve different
        # lr/wd multipliers fall back to the per-param path (the vmapped
        # body resolves mults once, at the template's index).
        update_groups: List[List[str]] = []
        grouped_names = set()
        scan_plan = getattr(self._exec, "_scan_plan", None)
        if scan_plan is not None and _config.get("MXNET_TPU_GROUP_UPDATE"):
            pset = set(param_names)
            for names in scan_plan.var_lists.values():
                if len(names) < 2 or any(n not in pset for n in names):
                    continue        # fixed/frozen member: eager per-param
                mults = {
                    (optimizer._resolve_mult(optimizer.lr_mult,
                                             name2idx[n]),
                     optimizer._resolve_mult(optimizer.wd_mult,
                                             name2idx[n]))
                    for n in names}
                if len(mults) != 1:
                    continue
                update_groups.append(list(names))
                grouped_names.update(names)
            if update_groups:
                _profiler.incr_counter("fused_update_grouped")
                _profiler.set_gauge("fused_update_groups",
                                    len(update_groups))
        single_names = [n for n in param_names if n not in grouped_names]

        def step(params, states, aux, inputs, frozen_vals, key, lr, t):
            def forward(p_in, aux_in, inp, k):
                def loss_fn(p):
                    outs, new_aux = fn({**p, **inp, **frozen_vals},
                                       aux_in, k, True)
                    return outs, new_aux

                if remat_policy is not None:
                    loss_fn = jax.checkpoint(loss_fn, policy=remat_policy)
                (outs, new_aux), vjp = jax.vjp(loss_fn, p_in)
                cts = [jnp.ones_like(o) for o in outs]
                grads = vjp((cts, {k2: jnp.zeros_like(v)
                                   for k2, v in new_aux.items()}))[0]
                return outs, new_aux, grads

            if accum > 1:
                micro = {n: v.reshape((accum, v.shape[0] // accum)
                                      + v.shape[1:])
                         for n, v in inputs.items()}

                def micro_step(carry, xs):
                    g_acc, aux_c = carry
                    # per-microbatch RNG: fold the step key once more so
                    # dropout draws differ across microbatches
                    outs, new_aux, grads = forward(
                        params, aux_c, xs["inp"],
                        jax.random.fold_in(key, xs["i"]))
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, grads)
                    # aux (BatchNorm stats) advance sequentially, exactly
                    # like N consecutive small-batch steps
                    return (g_acc, {**aux_c, **new_aux}), outs

                g0 = jax.tree_util.tree_map(jnp.zeros_like,
                                            {n: params[n]
                                             for n in param_names})
                (grads, new_aux), outs_stacked = jax.lax.scan(
                    micro_step, (g0, aux),
                    {"i": jnp.arange(accum, dtype=jnp.int32),
                     "inp": micro})
                if accum_scale != 1.0:
                    grads = {n: g * accum_scale for n, g in grads.items()}
                outs = [o.reshape((-1,) + o.shape[2:])
                        for o in outs_stacked]
            else:
                outs, new_aux, grads = forward(params, aux, inputs, key)
            new_params, new_states = {}, {}
            for n in single_names:
                w, s = optimizer.raw_update(
                    name2idx[n], params[n], grads[n], states[n], lr=lr, t=t)
                new_params[n] = w
                new_states[n] = s
            for names in update_groups:
                idx0 = name2idx[names[0]]
                w_stk = jnp.stack([params[n] for n in names])
                g_stk = jnp.stack([grads[n] for n in names])
                s_stk = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *[states[n] for n in names])

                def _one(w, g, s, _i=idx0):
                    return optimizer.raw_update(_i, w, g, s, lr=lr, t=t)

                nw, ns = jax.vmap(_one)(w_stk, g_stk, s_stk)
                for i, n in enumerate(names):
                    new_params[n] = nw[i]
                    new_states[n] = jax.tree_util.tree_map(
                        lambda x, _i=i: x[_i], ns)
            return outs, new_params, new_states, new_aux

        self._fused_num_update = self._optimizer.num_update
        self._fused_compiles = 0

        # ---- non-finite step guard (MXNET_TPU_NANCHECK): a device-side
        # isfinite reduction chained onto every fused step — same
        # pattern as device metrics, zero host syncs; the flags are
        # fetched once per epoch at the log boundary (_nancheck_poll),
        # where warn logs and abort raises naming the first non-finite
        # output. off = nothing built, nothing chained.
        self._nancheck_mode = _config.get("MXNET_TPU_NANCHECK")
        self._nancheck_fn = None
        self._nancheck_idx = ()
        self._nan_flags = None

        def run(data_batch):
            ex = self._exec
            self._load_batch(data_batch)
            params = {n: ex.arg_dict[n].data for n in param_names}
            states = self._fused_states
            aux = {n: a.data for n, a in ex.aux_dict.items()}
            inputs = {n: ex.arg_dict[n].data for n in
                      (set(self._data_names) | set(self._label_names)
                       | set(self._state_names))
                      if n in ex.arg_dict}
            frozen_vals = {n: ex.arg_dict[n].data for n in frozen}
            ex._step += 1
            key = jax.random.fold_in(ex._base_key, ex._step)
            self._fused_num_update += 1
            t = self._fused_num_update
            self._optimizer.num_update = t
            if self._optimizer.lr_scheduler is not None:
                lr = self._optimizer.lr_scheduler(t)
            else:
                lr = self._optimizer.lr
            call_args = (params, states, aux, inputs, frozen_vals, key,
                         jnp.asarray(lr, jnp.float32),
                         jnp.asarray(t, jnp.int32))
            with _obs_compiles.scope("fused_step", self._obs_sig):
                if self._fused_call is not None:
                    # AOT path: a deserialized (or explicitly compiled)
                    # executable — no jit dispatch, no trace, no compile
                    outs, new_params, new_states, new_aux = \
                        self._fused_call(*call_args)
                elif self._fused_aot_key is not None:
                    outs, new_params, new_states, new_aux = \
                        self._fused_aot_first(call_args)
                else:
                    outs, new_params, new_states, new_aux = \
                        self._fused_jit(*call_args)
            if self._nancheck_mode != "off":
                self._nancheck_accumulate(outs)
            if accum > 1:
                _profiler.incr_counter("accum_steps", accum)
            n = self._obs_steps + 1
            self._obs_steps = n
            if n == _obs_mfu.OBS_WARMUP_STEPS:
                # rate window opens after the compile steps; report()
                # closes it (and re-opens) at each collect
                self._obs_t0 = time.perf_counter()
            cache_size = getattr(self._fused_jit, "_cache_size", None)
            if cache_size is not None:
                # steady-state recompiles are a bug the async tests assert
                # against; count executable-cache growth past the warmup
                # compile (shape churn, accidental static arg drift)
                n = cache_size()
                if n > self._fused_compiles:
                    if self._fused_compiles > 0:
                        _profiler.incr_counter("loop_recompile",
                                               n - self._fused_compiles)
                    self._fused_compiles = n
            if ex._sync_host_callbacks:
                # callback-bearing program: execute synchronously with
                # the frontend (see executor.py / operator.py — the
                # async-drain deadlock)
                ex._forced_sync(outs)
            for n in param_names:
                ex.arg_dict[n]._data = new_params[n]
                ex.arg_dict[n]._version += 1
            for n, v in new_aux.items():
                ex.aux_dict[n]._data = v
                ex.aux_dict[n]._version += 1
            self._fused_states = new_states
            self._fused_out = [nd.NDArray(o) for o in outs]
            ex._outputs = self._fused_out
            ex._pending = None
            self._params_dirty = True

        # obs identity for compile attribution + the MFU collector; the
        # static FLOP estimate is invalidated here because a rebuild means
        # shapes (reshape) or structure changed
        self._obs_label = "fused_step:%s" % (
            self._output_names[0] if self._output_names else "?")
        self._obs_sig = (self._obs_label,
                         tuple((d.name, tuple(d.shape))
                               for d in self._data_shapes or ()))
        self._obs_flops_per_step = None
        _obs_mfu.register_executor(self)

        if getattr(self, "_fused_states", None) is None or \
                set(self._fused_states) != set(param_names):
            self._fused_states = make_states()

        # ---- AOT warm start (MXNET_TPU_COMPILE_CACHE): key the fused
        # step's executable on everything its trace bakes in, so a
        # restarted process deserializes instead of compiling. Fenced to
        # single-device programs (aot.py: deserialized multi-device
        # executables mis-execute on this jax version).
        self._fused_call = None
        self._fused_aot_key = None
        if _config.get("MXNET_TPU_COMPILE_CACHE"):
            from .. import aot as _aot
            if self._mesh is not None:
                _profiler.incr_counter("aot_skip_multidevice")
            elif _aot.supported():
                try:
                    from .. import amp as _amp
                    opt = self._optimizer
                    sig_parts = (
                        "fused_step", self._symbol.tojson(),
                        sorted((n, tuple(a.shape), str(a.dtype))
                               for n, a in self._exec.arg_dict.items()),
                        sorted((n, tuple(a.shape), str(a.dtype))
                               for n, a in self._exec.aux_dict.items()),
                        tuple(param_names), tuple(frozen),
                        sorted(self._grad_req.items()),
                        opt._fused_static_key(),
                        # statics the module step bakes (FusedUpdater
                        # passes these dynamically; this trace does not)
                        opt.wd, opt.rescale_grad, opt.clip_gradient,
                        sorted(opt.lr_mult.items()),
                        sorted(opt.wd_mult.items()),
                        sorted(opt.idx2name.items()),
                        accum, accum_scale, remat_name,
                        self._exec._scan_plan.n_layers
                        if self._exec._scan_plan is not None else 0,
                        (_amp.active(),
                         str(_amp.compute_dtype()) if _amp.active()
                         else ""),
                    )
                    self._fused_aot_key = _aot.digest(sig_parts)
                except Exception:                           # noqa: BLE001
                    # unkeyable configuration (unhashable optimizer
                    # statics): no warm start, plain jit dispatch
                    self._fused_aot_key = None
        if self._mesh is not None:
            # pin updated params to their declared shardings — otherwise
            # GSPMD may pick a different output layout after the first
            # step and the user-declared tp partitioning drifts — and pin
            # updated optimizer states to the shardings make_states placed
            # the INPUT states on: with the inputs committed, GSPMD is
            # free to pick a different layout for the returned state (a
            # replicated bias's momentum whose grad arrives model-sharded,
            # say), and a donated input cannot alias an output of a
            # different per-device size
            param_sh = {n: self._sharding_for(n) for n in param_names}
            state_sh = jax.tree_util.tree_map(lambda x: x.sharding,
                                              self._fused_states)
            self._fused_jit = jax.jit(
                step, donate_argnums=(0, 1, 2),
                out_shardings=(None, param_sh, state_sh, None))
        else:
            self._fused_jit = jax.jit(step, donate_argnums=(0, 1, 2))
        self._fused = run

    def _fused_aot_first(self, call_args):
        """First fused dispatch under MXNET_TPU_COMPILE_CACHE: load the
        serialized executable for this signature, or AOT-compile
        (``jit.lower().compile()``) and serialize it for the next
        process. Either way subsequent steps call a concrete executable
        — zero jit dispatch overhead, zero recompiles by construction."""
        from .. import aot as _aot
        name, key = "fused_step", self._fused_aot_key
        runner = _aot.load(name, key)
        if runner is not None:
            # first call through a deserialized executable runs on
            # COPIES of the donated trees: if the entry is unusable the
            # live buffers stay valid for the fresh-compile fallback.
            # The tiny per-shape copy jits get their own compile scope —
            # they must not show up as "the fused step compiled" in the
            # warm-start accounting (the CI gate asserts zero there)
            with _obs_compiles.scope("aot_first_copy"):
                safe = jax.tree_util.tree_map(jnp.copy, call_args[:3])
            try:
                out = runner(*safe, *call_args[3:])
            except Exception as exc:                        # noqa: BLE001
                _profiler.incr_counter("aot_error")
                self.logger.warning(
                    "aot: cached fused-step executable failed (%s); "
                    "recompiling", exc)
                runner = None
            else:
                self._fused_call = runner
                self._fused_aot_key = None
                return out
        try:
            # compile fresh (bypassing jax's persistent cache): a
            # cache-loaded executable cannot be re-serialized
            with _aot.bypass_persistent_cache():
                compiled = self._fused_jit.lower(*call_args).compile()
        except Exception:                                   # noqa: BLE001
            # lowering path failed (never expected); keep plain dispatch
            self._fused_aot_key = None
            return self._fused_jit(*call_args)
        _aot.store(name, key, compiled)
        self._fused_call = compiled
        self._fused_aot_key = None
        return compiled(*call_args)

    def _check_accum_needs_fused(self) -> None:
        if getattr(self, "_grad_accum", 1) > 1:
            raise MXNetError(
                "grad_accum > 1 requires the fused train step; this "
                "binding falls back to eager update (kvstore/custom "
                "updater) which cannot microbatch")

    def set_grad_accum(self, n: int) -> None:
        """Microbatch gradient accumulation: the fused step splits every
        bound batch into ``n`` equal microbatches run through one
        ``lax.scan`` with gradient carry, so activation memory scales
        with the microbatch while the optimizer sees the full-batch
        gradient (``fit(grad_accum=n)`` routes here). ``n=1`` restores
        the flat step."""
        n = int(n)
        if n < 1:
            raise MXNetError("grad_accum must be >= 1, got %d" % n)
        if n != getattr(self, "_grad_accum", 1):
            self._grad_accum = n
            if self.optimizer_initialized:
                self._build_fused_step()

    def _fit_step(self, data_batch):
        """One fused train step; fit() uses this when available."""
        if self._fused is None:
            self.forward_backward(data_batch)
            self.update()
        else:
            self._fused(data_batch)

    # ------------------------------------------------- non-finite guard
    def _nancheck_accumulate(self, outs):
        """Chain one tiny jitted reduction onto this step's outputs:
        per-output "ever went non-finite" flags accumulated ON DEVICE
        (async dispatch — the step loop never syncs for it). Integer
        outputs are skipped; a program with no inexact outputs disables
        the guard for this bind."""
        import jax
        import jax.numpy as jnp
        if self._nancheck_fn is None:
            idx = tuple(i for i, o in enumerate(outs)
                        if jnp.issubdtype(o.dtype, jnp.inexact))
            if not idx:
                self._nancheck_mode = "off"
                return
            self._nancheck_idx = idx

            @jax.jit
            def chained(flags, outs_t):
                return tuple(f | ~jnp.all(jnp.isfinite(outs_t[i]))
                             for f, i in zip(flags, idx))

            self._nancheck_fn = chained
        flags = self._nan_flags
        if flags is None:
            flags = tuple(jnp.zeros((), jnp.bool_)
                          for _ in self._nancheck_idx)
        self._nan_flags = self._nancheck_fn(flags, tuple(outs))

    def _nancheck_poll(self) -> Optional[str]:
        """The log-boundary host fetch of the chained flags (the ONE
        sync, same place as the metric sync): returns the name of the
        first non-finite output, or None. Resets the accumulator so
        each epoch is judged on its own steps."""
        flags = self._nan_flags
        if flags is None:
            return None
        import jax
        host = [bool(v) for v in jax.device_get(flags)]
        self._nan_flags = None
        for i, hit in zip(self._nancheck_idx, host):
            if hit:
                names = self._output_names or []
                return names[i] if i < len(names) else "output%d" % i
        return None

    # ------------------------------------------------------------- compute
    def _place_value(self, name, arr):
        """One input's device placement: dtype cast + shard/replicate per
        the bound mesh (or plain device_put). Shared by the critical-path
        ``_load_batch`` and the background device-prefetch stage, so a
        prefetched batch lands exactly where a synchronous one would."""
        val = arr.data if isinstance(arr, nd.NDArray) else \
            jnp.asarray(np.asarray(arr))
        tgt = self._exec.arg_dict.get(name)
        if tgt is None:
            return None
        if val.dtype != tgt.data.dtype:
            val = val.astype(tgt.data.dtype)
        if self._mesh is not None:
            if val.ndim == 0:
                # rank-0 inputs have no batch dim to shard (bind-time
                # validation skips them the same way) — replicate
                from ..parallel.mesh import replicate
                val = replicate(self._mesh, val)
            elif self._batch_sharding is not None:
                # unified layout: the batch shards over BOTH data-parallel
                # axes (data, fsdp) — validated at bind
                val = jax.device_put(val, self._batch_sharding)
            elif "data" in self._mesh.axis_names:
                from ..parallel.mesh import shard_batch
                val = shard_batch(self._mesh, val)
            else:
                # pure tensor-parallel mesh: the batch is replicated
                from ..parallel.mesh import replicate
                val = replicate(self._mesh, val)
        else:
            val = jax.device_put(val, self._context[0].jax_device)
        return val

    def _load_batch(self, data_batch):
        """Place batch data/labels into the bound args; with a mesh, inputs
        are batch-sharded over the `data` axis (the TPU form of
        _load_data/_load_label slicing in executor_group.py:31-75). Batches
        the device-prefetch stage already placed (``_mx_placed``) are
        swapped in without touching the device."""
        ex = self._exec
        data = data_batch.data
        labels = data_batch.label or []
        placed = getattr(data_batch, "_mx_placed", None)

        def place(name, arr):
            if placed is not None and name in placed:
                val = placed[name]
            else:
                val = self._place_value(name, arr)
                if val is None:
                    return
            tgt = ex.arg_dict.get(name)
            if tgt is None:
                return
            tgt._data = val
            tgt._version += 1

        for name, arr in zip(self._data_names, data):
            place(name, arr)
        for name, arr in zip(self._label_names, labels):
            place(name, arr)

    # ----------------------------------------------------------- async loop
    def _async_capable(self) -> bool:
        """True when fit() may run the bounded-in-flight async loop: the
        fused step exists and the bound program carries no host callbacks
        (callback programs must stay synchronous — executor.py
        requires_sync_loop, the PR 2 deadlock)."""
        return (self._fused is not None and self._exec is not None
                and not self._exec.requires_sync_loop)

    def _step_token(self):
        """Completion token of the last fused step (its raw output arrays)
        for the InflightWindow; None when no fused step ran."""
        if self._fused_out is None:
            return None
        return tuple(o.data for o in self._fused_out)

    def _device_placer(self):
        """Callable the PrefetchingIter device stage runs in a background
        thread: issues the H2D placement (honoring mesh input shardings)
        for every data/label input and stashes the placed arrays on the
        batch; ``_load_batch`` then swaps them in with zero device work on
        the critical path."""
        if self._exec is None:
            return None

        def place_batch(data_batch):
            placed = {}
            for name, arr in zip(self._data_names, data_batch.data or []):
                val = self._place_value(name, arr)
                if val is not None:
                    placed[name] = val
            for name, arr in zip(self._label_names,
                                 data_batch.label or []):
                val = self._place_value(name, arr)
                if val is not None:
                    placed[name] = val
            data_batch._mx_placed = placed
            return data_batch

        return place_batch

    def _update_metric_device(self, eval_metric, labels) -> bool:
        """Device-resident metric update: hand the metric the step's own
        device arrays (labels from the bound args — already placed/sharded
        — and the fused step's outputs) so accumulation is a chained
        device reduction with no host sync. Returns False when the metric
        cannot (custom/numpy metrics) and the caller must run the host
        path."""
        if not eval_metric.device_capable():
            return False
        ex = self._exec
        label_names = self._label_names or \
            [d.name for d in self._label_shapes]
        label_dict = {}
        for name, arr in zip(label_names, labels or []):
            bound = ex.arg_dict.get(name)
            label_dict[name] = bound.data if bound is not None else arr
        preds = dict(zip(self._output_names, self.get_outputs()))
        return eval_metric.update_dict_device(label_dict, preds)

    def forward(self, data_batch, is_train=None):
        """(reference: module.py:556)."""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        self._load_batch(data_batch)
        self._exec.forward(is_train=is_train)
        if is_train:
            self._params_dirty = True  # aux states may advance

    def backward(self, out_grads=None):
        """(reference: module.py:598)."""
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Apply gradients (reference: module.py:615 →
        model.py:106 _update_params_on_kvstore)."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        if self._kvstore is not None:
            for idx, name in enumerate(self._param_names):
                grad = self._exec.grad_dict.get(name)
                if grad is None:
                    continue
                weight = self._exec.arg_dict[name]
                self._kvstore.push(idx, grad)
                if self._update_on_kvstore:
                    self._kvstore.pull(idx, out=weight)
                else:
                    self._kvstore.pull(idx, out=grad)
                    self._updater(idx, grad, weight)
        else:
            items = []
            for idx, name in enumerate(self._param_names):
                grad = self._exec.grad_dict.get(name)
                if grad is None:
                    continue
                items.append((idx, self._exec.arg_dict[name], grad))
            # same fused whole-model step as gluon Trainer.step: all
            # updates in one structure-cached jitted program, per-param
            # eager dispatch as the fallback
            if self._fused_updater is not None \
                    and self._fused_updater.try_step(self._updater, items):
                return
            for idx, weight, grad in items:
                self._updater(idx, grad, weight)

    def get_outputs(self, merge_multi_context=True):
        """(reference: module.py get_outputs). One program ⇒ already
        merged."""
        assert self.binded and self.params_initialized
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        """(reference: module.py get_input_grads)."""
        assert self.binded and self.params_initialized \
            and self.inputs_need_grad
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return [self._exec.arg_dict[n] for n in self._state_names]

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        if states is not None:
            for name, val in zip(self._state_names, states):
                self._exec.arg_dict[name]._data = \
                    val.data if isinstance(val, nd.NDArray) else \
                    jnp.asarray(val)
                self._exec.arg_dict[name]._version += 1
        else:
            for name in self._state_names:
                arr = self._exec.arg_dict[name]
                arr._data = jnp.full_like(arr.data, value)
                arr._version += 1

    def update_metric(self, eval_metric, labels):
        """(reference: module.py update_metric → executor_group
        update_metric)."""
        labels = {name: arr for name, arr in
                  zip(self._label_names or
                      [d.name for d in self._label_shapes], labels)}
        preds = dict(zip(self._output_names, self.get_outputs()))
        eval_metric.update_dict(labels, preds)

    def reshape(self, data_shapes, label_shapes=None):
        """(reference: module.py reshape). Shapes re-bind lazily: XLA caches
        one executable per shape signature."""
        assert self.binded
        self._data_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                             for x in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [x if isinstance(x, DataDesc)
                                  else DataDesc(*x) for x in label_shapes]
        kw = {d.name: d.shape for d in self._data_shapes}
        if label_shapes:
            kw.update({d.name: d.shape for d in self._label_shapes})
        self._exec = self._exec.reshape(**kw)
        if self.optimizer_initialized:
            self._build_fused_step()

    def install_monitor(self, mon):
        """(reference: module.py install_monitor)."""
        assert self.binded
        mon.install(self._exec)

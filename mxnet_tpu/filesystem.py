"""``mx.filesystem`` — URI-scheme file IO (the dmlc-core Stream layer).

Reference: dmlc-core's ``dmlc::Stream::Create`` dispatches on URI scheme
(local path, ``s3://``, ``hdfs://``) so RecordIO datasets and checkpoints
work on any storage backend (SURVEY.md §2.11; e.g. model.py save/load via
dmlc Stream). Same design here: ``open_uri(uri, mode)`` returns a local
file path — remote objects are staged through a temp file on read and
uploaded on close for write — so every consumer (recordio, nd.save/load,
checkpoints) keeps using ordinary file APIs.

Backends:
* local paths / ``file://`` — direct.
* ``s3://bucket/key`` — via boto3 when installed; a clear error otherwise
  (this image has no egress, so the backend is gate-tested with a stub).
* ``hdfs://`` — via pyarrow.fs when installed.
* custom — ``register_scheme("myfs", open_fn)`` plugs in anything.
"""
from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
from typing import Callable, Dict

__all__ = ["open_uri", "register_scheme", "scheme_of", "exists"]

_SCHEMES: Dict[str, Callable] = {}


def register_scheme(scheme: str, opener: Callable) -> None:
    """Register ``opener(path, mode) -> context manager yielding a local
    file path`` for ``scheme://`` URIs (dmlc's Stream registry role)."""
    _SCHEMES[scheme] = opener


def scheme_of(uri: str) -> str:
    if "://" in uri:
        return uri.split("://", 1)[0]
    return ""


@contextlib.contextmanager
def _local(path: str, mode: str):
    yield path


def _require_boto3():
    try:
        import boto3
    except ImportError:
        raise IOError(
            "s3:// URIs need boto3 (not installed in this environment); "
            "stage the file locally or register_scheme('s3', ...) with a "
            "custom opener") from None
    return boto3


@contextlib.contextmanager
def _s3(path: str, mode: str):
    # path = bucket/key
    if "a" in mode:
        raise IOError("append mode is not supported for s3:// URIs "
                      "(objects are immutable; rewrite with 'w')")
    boto3 = _require_boto3()
    bucket, _, key = path.partition("/")
    s3 = boto3.client("s3")
    with tempfile.NamedTemporaryFile(delete=False) as tmp:
        tmp_path = tmp.name
    try:
        if "r" in mode:
            s3.download_file(bucket, key, tmp_path)
        yield tmp_path
        if "w" in mode or "a" in mode:
            s3.upload_file(tmp_path, bucket, key)
    finally:
        os.unlink(tmp_path)


@contextlib.contextmanager
def _hdfs(path: str, mode: str):
    if "a" in mode:
        raise IOError("append mode is not supported for hdfs:// URIs; "
                      "rewrite with 'w'")
    try:
        from pyarrow import fs as pafs
    except ImportError:
        raise IOError(
            "hdfs:// URIs need pyarrow (not installed in this "
            "environment); register_scheme('hdfs', ...) to override"
        ) from None
    host, _, rest = path.partition("/")
    hdfs = pafs.HadoopFileSystem(host or "default")
    with tempfile.NamedTemporaryFile(delete=False) as tmp:
        tmp_path = tmp.name
    try:
        if "r" in mode:
            with hdfs.open_input_stream("/" + rest) as src, \
                    open(tmp_path, "wb") as dst:
                shutil.copyfileobj(src, dst)
        yield tmp_path
        if "w" in mode or "a" in mode:
            with open(tmp_path, "rb") as src, \
                    hdfs.open_output_stream("/" + rest) as dst:
                shutil.copyfileobj(src, dst)
    finally:
        os.unlink(tmp_path)


register_scheme("", _local)
register_scheme("file", _local)
register_scheme("s3", _s3)
register_scheme("hdfs", _hdfs)


def open_uri(uri: str, mode: str = "r"):
    """Context manager yielding a LOCAL file path for ``uri``.

    Local paths pass through; remote schemes stage via a temp file
    (download before the body for 'r', upload after it for 'w')."""
    scheme = scheme_of(uri)
    if scheme not in _SCHEMES:
        raise IOError("no filesystem registered for scheme %r (have %s)"
                      % (scheme, sorted(s for s in _SCHEMES if s)))
    path = uri.split("://", 1)[1] if scheme else uri
    return _SCHEMES[scheme](path, mode)


def exists(uri: str) -> bool:
    """Existence probe. Local/file:// use os.path.exists; s3/hdfs use
    cheap metadata probes (no download). Missing-dependency errors
    propagate — a host without boto3 must not report checkpoints absent.
    Custom schemes fall back to attempting a read open."""
    scheme = scheme_of(uri)
    if scheme in ("", "file"):
        path = uri.split("://", 1)[1] if scheme else uri
        return os.path.exists(path)
    if scheme == "s3":
        boto3 = _require_boto3()
        bucket, _, key = uri.split("://", 1)[1].partition("/")
        s3 = boto3.client("s3")
        try:
            s3.head_object(Bucket=bucket, Key=key)
            return True
        except s3.exceptions.ClientError:
            return False
    if scheme == "hdfs":
        from pyarrow import fs as pafs   # ImportError propagates
        host, _, rest = uri.split("://", 1)[1].partition("/")
        info = pafs.HadoopFileSystem(host or "default").get_file_info(
            "/" + rest)
        return info.type != pafs.FileType.NotFound
    if scheme not in _SCHEMES:
        raise IOError("no filesystem registered for scheme %r" % scheme)
    try:
        with open_uri(uri, "r"):
            return True
    except FileNotFoundError:
        return False
    except OSError:
        return False

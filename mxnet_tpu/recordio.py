"""RecordIO — the dataset container format.

Reference: ``python/mxnet/recordio.py`` (MXRecordIO:36, MXIndexedRecordIO,
IRHeader, pack/unpack/pack_img/unpack_img) over dmlc-core's RecordIO codec
(SURVEY.md §2.8, §2.11; design doc docs/architecture/note_data_loading.md).

Binary layout (dmlc recordio): per record a uint32 magic ``0xced7230a``, a
uint32 ``lrecord`` whose upper 3 bits are a continuation flag and lower 29
bits the payload length, then the payload padded to 4-byte alignment.
Payloads that fit 29 bits are written as single cflag=0 records; larger
ones are chained as cflag 1/2/3 parts (dmlc-core writer behavior), and the
reader reassembles either form.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct
from collections import namedtuple
from typing import List, Optional

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xced7230a


class MXRecordIO(object):
    """Sequential record file reader/writer (reference: recordio.py:36)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        from . import filesystem as _fs
        if self.flag not in ("r", "w"):   # before staging: no temp leak
            raise ValueError("Invalid flag %s" % self.flag)
        path = self.uri
        self._staged = None
        if _fs.scheme_of(self.uri):
            # remote URI (s3://, hdfs://, ...): stage through a local file
            # the way dmlc::Stream wraps remote filesystems (SURVEY §2.11)
            self._staged = _fs.open_uri(
                self.uri, "r" if self.flag == "r" else "w")
            path = self._staged.__enter__()
        if self.flag == "w":
            self.handle = open(path, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(path, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)

    def close(self):
        if self.handle is not None:
            self.handle.close()
            self.handle = None
            if getattr(self, "_staged", None) is not None:
                self._staged.__exit__(None, None, None)  # uploads on write
                self._staged = None

    def reset(self):
        """(reference: recordio.py reset — reopen for reading)."""
        self.close()
        self.open()

    def write(self, buf: bytes):
        """(reference: recordio.py write).

        Payloads >= 2**29 bytes don't fit the 29-bit length field and are
        split into a cflag 1/2/3 multi-part chain, mirroring dmlc-core's
        writer; ``read`` already reassembles such chains.
        """
        assert self.writable
        _max = (1 << 29) - 1
        chunks = [buf[i:i + _max] for i in range(0, len(buf), _max)] or [b""]
        for i, chunk in enumerate(chunks):
            if len(chunks) == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == len(chunks) - 1:
                cflag = 3
            else:
                cflag = 2
            length = len(chunk)
            self.handle.write(
                struct.pack("<II", _kMagic, (cflag << 29) | length))
            self.handle.write(chunk)
            pad = (-length) % 4
            if pad:
                self.handle.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        """(reference: recordio.py read). Returns None at EOF."""
        assert not self.writable
        parts = []
        while True:
            offset = self.handle.tell()
            header = self.handle.read(8)
            if len(header) < 8:
                if parts:  # EOF mid-chain: a truncated multi-part record
                    raise IOError(
                        "truncated multi-part record at EOF in %s" % self.uri)
                return None
            magic, lrec = struct.unpack("<II", header)
            if magic != _kMagic:
                raise IOError(
                    "Invalid magic number 0x%08x at offset %d of record "
                    "file %s (expected 0x%08x — a corrupt file, or a "
                    "seek to a non-record boundary)"
                    % (magic, offset, self.uri, _kMagic))
            cflag = lrec >> 29
            length = lrec & ((1 << 29) - 1)
            data = self.handle.read(length)
            if len(data) < length:
                # a short payload read used to flow downstream and die
                # as an opaque struct.unpack error — name the truncation
                raise IOError(
                    "truncated record at offset %d of %s: header "
                    "promises %d payload bytes, file ends after %d"
                    % (offset, self.uri, length, len(data)))
            pad = (-length) % 4
            if pad:
                self.handle.read(pad)
            parts.append(data)
            # cflag: 0 = whole record; 1 = begin; 2 = middle; 3 = end
            if cflag in (0, 3):
                return b"".join(parts)

    def tell(self) -> int:
        return self.handle.tell()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record file keyed by an index sidecar (reference:
    recordio.py MXIndexedRecordIO; idx file = "key\\toffset" lines).

    Index entries are VALIDATED against the record file at load: an
    offset past (or too near) EOF cannot hold a record header, so it is
    rejected here with the index key named — instead of surfacing later
    as an opaque ``struct.unpack``/magic error from whatever
    ``read_idx`` call happens to hit it first. ``read_idx`` wraps the
    remaining in-file corruption shapes (bad magic at a valid offset,
    truncated payload) the same way: every error names the index key
    and the files involved (tamper tests: tests/test_io.py).
    """

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys: List = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            # a record needs at least its 8-byte header before EOF; an
            # offset beyond that bound indexes nothing
            self.handle.seek(0, 2)
            fsize = self.handle.tell()
            self.handle.seek(0)
            with open(idx_path) as fin:
                for lineno, raw in enumerate(fin, 1):
                    raw = raw.strip()
                    if not raw:
                        continue
                    fields = raw.split("\t")
                    try:
                        key = key_type(fields[0])
                        offset = int(fields[1])
                    except (IndexError, ValueError) as exc:
                        raise IOError(
                            "malformed index entry at %s:%d (%r): %s"
                            % (idx_path, lineno, raw, exc))
                    if offset < 0 or offset + 8 > fsize:
                        raise IOError(
                            "index key %r at %s:%d points at offset %d "
                            "but %s holds only %d bytes — the index does "
                            "not match this record file (stale or "
                            "corrupt .idx)"
                            % (key, idx_path, lineno, offset, uri, fsize))
                    self.idx[key] = offset
                    self.keys.append(key)

    def close(self):
        if self.handle is not None and self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (str(key), self.idx[key]))
        super().close()

    def seek(self, idx):
        """(reference: recordio.py seek)."""
        assert not self.writable
        if idx not in self.idx:
            raise KeyError(
                "key %r not in index %s (%d keys)"
                % (idx, self.idx_path, len(self.idx)))
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx) -> bytes:
        self.seek(idx)
        try:
            buf = self.read()
        except (IOError, OSError, struct.error) as exc:
            raise IOError(
                "reading index key %r (offset %d) of %s failed: %s"
                % (idx, self.idx[idx], self.uri, exc))
        if buf is None:
            raise IOError(
                "index key %r points at offset %d of %s, which is EOF — "
                "the index does not match this record file"
                % (idx, self.idx[idx], self.uri))
        return buf

    def write_idx(self, idx, buf: bytes):
        """(reference: recordio.py write_idx)."""
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a (header, payload) into a record string (reference:
    recordio.py pack). Multi-label: header.label is an array and header.flag
    its length."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        buf = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                          header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        buf = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
        buf += label.tobytes()
    return buf + s


def unpack(s: bytes):
    """(reference: recordio.py unpack). Returns (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[: header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header: IRHeader, img, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    """Encode image + pack (reference: recordio.py pack_img, OpenCV path)."""
    import cv2
    encode_params = None
    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    assert ret, "failed to encode image"
    return pack(header, buf.tobytes())


def unpack_img(s: bytes, iscolor: int = -1):
    """(reference: recordio.py unpack_img). Returns (IRHeader, BGR ndarray)."""
    import cv2
    header, s = unpack(s)
    img = np.frombuffer(s, dtype=np.uint8)
    img = cv2.imdecode(img, iscolor)
    return header, img

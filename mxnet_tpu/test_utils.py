"""Testing oracles.

Reference: ``python/mxnet/test_utils.py`` (SURVEY.md §4) — numpy is the
reference implementation, ``check_numeric_gradient`` (test_utils.py:439)
validates every backward against central finite differences, and
``check_consistency`` (test_utils.py:784) cross-checks contexts/dtypes.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Union

import numpy as np

from .context import Context, cpu, current_context
from . import ndarray as nd
from . import autograd

__all__ = [
    "default_context", "set_default_context", "assert_almost_equal",
    "almost_equal", "same", "rand_ndarray", "random_arrays",
    "check_numeric_gradient", "check_symbolic_forward",
    "check_symbolic_backward", "check_consistency", "numeric_grad",
]

_default_ctx: Optional[Context] = None


def default_context() -> Context:
    """(reference: test_utils.py:47 — swappable via env so the same suite
    runs on CPU interpreter or a real TPU chip)."""
    if _default_ctx is not None:
        return _default_ctx
    env = os.environ.get("MXNET_TEST_DEFAULT_CTX")
    if env:
        kind, _, idx = env.partition(":")
        return Context(kind, int(idx or 0))
    return current_context()


def set_default_context(ctx: Context) -> None:
    global _default_ctx
    _default_ctx = ctx


def same(a: np.ndarray, b: np.ndarray) -> bool:
    return np.array_equal(np.asarray(a), np.asarray(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-8) -> bool:
    return np.allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def _to_np(x):
    if isinstance(x, nd.NDArray):
        return x.asnumpy()
    return np.asarray(x)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")) -> None:
    """(reference: test_utils.py:148)."""
    a, b = _to_np(a), _to_np(b)
    if a.shape != b.shape:
        raise AssertionError(
            "shape mismatch: %s=%s vs %s=%s" % (names[0], a.shape, names[1], b.shape))
    if not np.allclose(a, b, rtol=rtol, atol=atol):
        err = np.abs(a - b)
        rel = err / (np.abs(b) + atol)
        idx = np.unravel_index(np.argmax(rel), rel.shape)
        raise AssertionError(
            "%s and %s differ: max rel err %g at %s (%g vs %g), "
            "max abs err %g" % (names[0], names[1], float(rel.max()), idx,
                                a[idx], b[idx], float(err.max())))


def random_arrays(*shapes, dtype=np.float32) -> List[np.ndarray]:
    """(reference: test_utils.py random_arrays)."""
    arrays = [np.random.randn(*s).astype(dtype) if s else
              np.array(np.random.randn(), dtype=dtype) for s in shapes]
    return arrays


def rand_ndarray(shape, ctx=None, dtype=np.float32) -> nd.NDArray:
    return nd.array(np.random.randn(*shape).astype(dtype), ctx=ctx)


# ------------------------------------------------------------------ gradient


def numeric_grad(f: Callable[[Mapping[str, np.ndarray]], float],
                 location: Dict[str, np.ndarray],
                 wrt: Sequence[str],
                 eps: float = 1e-4) -> Dict[str, np.ndarray]:
    """Central-difference gradient of a scalar function of named numpy arrays
    (the inner loop of reference test_utils.py:439 check_numeric_gradient)."""
    grads = {}
    for name in wrt:
        base = location[name]
        g = np.zeros_like(base, dtype=np.float64)
        flat = base.reshape(-1)
        gflat = g.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            fp = f(location)
            flat[i] = orig - eps
            fm = f(location)
            flat[i] = orig
            gflat[i] = (fp - fm) / (2 * eps)
        grads[name] = g.reshape(base.shape)
    return grads


def check_numeric_gradient(fn: Union[Callable, "object"],
                           location: Union[Dict[str, np.ndarray], Sequence[np.ndarray]],
                           aux_states: Optional[Dict[str, np.ndarray]] = None,
                           numeric_eps: float = 1e-3,
                           rtol: float = 2e-2,
                           atol: float = 2e-3,
                           grad_nodes: Optional[Sequence[str]] = None,
                           ctx: Optional[Context] = None) -> None:
    """Finite-difference-check the autograd backward of ``fn``.

    ``fn`` is either a callable taking NDArrays (keyword by name for dict
    locations, positional for list locations) and returning one NDArray, or a
    Symbol (reference: test_utils.py:439 takes a Symbol; the callable form is
    the imperative-first equivalent). The output is reduced with a fixed
    random projection so the head gradient exercise is non-trivial.
    """
    if hasattr(fn, "list_arguments"):  # Symbol
        sym = fn
        args = sym.list_arguments()
        if isinstance(location, (list, tuple)):
            location = dict(zip(args, location))
        fwd = _symbol_forward_fn(sym, aux_states, ctx)
        return check_numeric_gradient(fwd, location, None, numeric_eps, rtol,
                                      atol, grad_nodes, ctx)

    if isinstance(location, (list, tuple)):
        location = {"arg%d" % i: v for i, v in enumerate(location)}
        positional = True
    else:
        positional = False
    location = {k: np.asarray(v, dtype=np.float64).astype(np.float32)
                for k, v in location.items()}
    names = list(location.keys())
    wrt = list(grad_nodes) if grad_nodes is not None else names

    proj = None  # fixed random projection, created at first forward

    def run_fwd(vals: Mapping[str, np.ndarray]):
        nonlocal proj
        nds = {k: nd.array(v.astype(np.float32), ctx=ctx) for k, v in vals.items()}
        out = fn(*nds.values()) if positional else fn(**nds)
        if isinstance(out, (list, tuple)):
            out = out[0]
        if proj is None:
            rng = np.random.RandomState(802)
            proj = rng.uniform(0.5, 1.5, size=out.shape).astype(np.float32)
        return nds, out

    def scalar_f(vals: Mapping[str, np.ndarray]) -> float:
        _, out = run_fwd(vals)
        return float(np.sum(out.asnumpy().astype(np.float64) * proj))

    # symbolic gradient via autograd
    nds = {k: nd.array(v.astype(np.float32), ctx=ctx) for k, v in location.items()}
    for k in wrt:
        nds[k].attach_grad()
    with autograd.record():
        out = fn(*nds.values()) if positional else fn(**nds)
        if isinstance(out, (list, tuple)):
            out = out[0]
    if proj is None:
        rng = np.random.RandomState(802)
        proj = rng.uniform(0.5, 1.5, size=out.shape).astype(np.float32)
    out.backward(out_grad=nd.array(proj))
    sym_grads = {k: nds[k].grad.asnumpy() for k in wrt}

    num_grads = numeric_grad(scalar_f, location, wrt, eps=numeric_eps)
    for k in wrt:
        assert_almost_equal(sym_grads[k], num_grads[k].astype(np.float32),
                            rtol=rtol, atol=atol,
                            names=("autograd[%s]" % k, "numeric[%s]" % k))


def _symbol_forward_fn(sym, aux_states, ctx):
    """Adapt a Symbol into an *imperative* callable so the evaluation is
    recorded on the autograd tape (the reference equivalently binds and runs
    the executor backward; here nd-level replay is the backward engine)."""
    from .symbol.symbol import _topo_order
    from .ndarray import imperative_invoke

    nodes = _topo_order(sym._entries)

    def fwd(**kwargs):
        vals = {}
        for node in nodes:
            if node.is_variable:
                if node.name in kwargs:
                    v = kwargs[node.name]
                elif aux_states and node.name in aux_states:
                    a = aux_states[node.name]
                    v = a if isinstance(a, nd.NDArray) else nd.array(a)
                else:
                    raise ValueError("missing input %r" % node.name)
                vals[(id(node), 0)] = v
                continue
            ins = [vals[(id(n), i)] for n, i in node.inputs]
            attrs = {k: v for k, v in node.attrs.items() if k != "name"}
            out = imperative_invoke(node.op, *ins, **attrs)
            outs = out if isinstance(out, (list, tuple)) else [out]
            for i, o in enumerate(outs):
                vals[(id(node), i)] = o
        results = [vals[(id(n), i)] for n, i in sym._entries]
        return results[0] if len(results) == 1 else results

    return fwd


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-6,
                           aux_states=None, ctx=None) -> None:
    """Forward outputs vs numpy expectation (reference: test_utils.py:552)."""
    outs = _eval_fn_or_sym(sym, location, aux_states, ctx)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    for i, (o, e) in enumerate(zip(outs, expected)):
        assert_almost_equal(o, e, rtol=rtol, atol=atol,
                            names=("output[%d]" % i, "expected[%d]" % i))


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-6, grad_nodes=None, ctx=None) -> None:
    """Backward grads vs numpy expectation (reference: test_utils.py:617)."""
    if hasattr(sym, "list_arguments"):
        fn = _symbol_forward_fn(sym, None, ctx)
        names = sym.list_arguments()
        if isinstance(location, (list, tuple)):
            location = dict(zip(names, location))
    else:
        fn = sym
        names = list(location.keys())
    nds = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
    wrt = list(grad_nodes) if grad_nodes is not None else list(nds)
    for k in wrt:
        nds[k].attach_grad()
    with autograd.record():
        out = fn(**nds)
        if isinstance(out, (list, tuple)):
            out = out[0]
    og = out_grads[0] if isinstance(out_grads, (list, tuple)) else out_grads
    out.backward(out_grad=nd.array(og))
    if isinstance(expected, dict):
        items = expected.items()
    else:
        items = zip(wrt, expected)
    for k, e in items:
        assert_almost_equal(nds[k].grad, e, rtol=rtol, atol=atol,
                            names=("grad[%s]" % k, "expected[%s]" % k))


def _eval_fn_or_sym(sym, location, aux_states, ctx):
    if hasattr(sym, "list_arguments"):
        names = sym.list_arguments()
        if isinstance(location, (list, tuple)):
            location = dict(zip(names, location))
        return sym.eval(ctx=ctx, aux_states=aux_states,
                        **{k: nd.array(v, ctx=ctx) for k, v in location.items()})
    nds = {k: nd.array(v, ctx=ctx) for k, v in location.items()} \
        if isinstance(location, dict) else [nd.array(v, ctx=ctx) for v in location]
    return sym(**nds) if isinstance(nds, dict) else sym(*nds)


def check_consistency(fn, locations, ctx_list=None, rtol=1e-3, atol=1e-5):
    """Run the same computation across contexts/dtypes and cross-compare
    (reference: test_utils.py:784 — cpu-fp32 vs gpu-fp16 etc.; here
    CPU interpreter vs accelerator and fp32 vs bf16)."""
    from .context import tpu, num_devices
    if ctx_list is None:
        ctx_list = [cpu(0)]
        if num_devices("tpu"):
            ctx_list.append(tpu(0))
    outs = []
    for ctx in ctx_list:
        nds = {k: nd.array(v, ctx=ctx) for k, v in locations.items()}
        o = fn(**nds)
        if isinstance(o, (list, tuple)):
            o = o[0]
        outs.append(o.asnumpy())
    for o in outs[1:]:
        assert_almost_equal(outs[0], o, rtol=rtol, atol=atol)
    return outs

"""Preemption-aware elastic training supervisor (ROADMAP item 4).

``fit`` already survives a preemption NOTICE: SIGTERM lands a final
synchronous checkpoint and exits 143 (PR 5). This module supplies the
missing half — the thing that *re-enters* training after the preemption:

* run the training program in a CHILD process (a supervisor that shares
  the training process dies with it — only a process boundary survives
  ``kill -9``);
* treat exit 143 (clean preemption) and any crash (signal death,
  non-zero exit) as a restartable event, bounded by
  ``MXNET_TPU_ELASTIC_MAX_RESTARTS`` with exponential backoff + jitter;
* re-probe the visible device set between attempts and re-launch the
  child at the NEW world size (on preemptible capacity the replacement
  slice is routinely smaller or larger than the one that died);
* the child resumes from the newest valid checkpoint
  (``resume_dir(base)``) — reshard-on-load re-lays every array out onto
  whatever mesh the new world size builds, so an 8-chip checkpoint
  restores onto 4 chips, 2, or 1 (``docs/architecture/elastic.md``).

The supervisor itself is deliberately framework-light: this module
touches only stdlib + the config/profiler modules, and the supervisor
process must never INITIALIZE a jax backend (``python -m`` necessarily
imports the package, which imports the jax library — but a backend pins
its device view for the life of the process, so every device query runs
in a throwaway subprocess instead: :func:`probe_world`). A regression
test runs the supervisor under an unresolvable ``JAX_PLATFORMS`` so any
in-process backend initialization fails loudly.

CLI::

    python -m mxnet_tpu.elastic [--max-restarts N] [--backoff S]
        [--world-schedule 8,4,2] -- python train.py --my-args

Environment exported to every attempt:

* ``MXNET_TPU_ELASTIC_ATTEMPT`` — 0-based attempt index (the training
  script can key per-attempt behavior on it; the fault drills do);
* ``MXNET_TPU_ELASTIC_RESUMED=1`` — on every attempt after the first;
* with a world schedule (the virtual-mesh test rig), ``XLA_FLAGS`` is
  rewritten with ``--xla_force_host_platform_device_count=<n>`` so the
  child binds at the scheduled world size.

Counters: ``elastic_restart`` (every re-entry), ``elastic_preempt``
(exit-143 children), ``elastic_crash`` (signal/non-zero children),
``elastic_reshard`` (re-entries whose world size changed) and the
``elastic_world`` gauge.
"""
from __future__ import annotations

import argparse
import logging
import os
import random as _pyrandom
import re
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

__all__ = ["Supervisor", "supervise", "resume_dir", "probe_world", "main"]

log = logging.getLogger(__name__)

_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count"


def resume_dir(base: str) -> Optional[str]:
    """``base`` if it holds at least one VALID checkpoint, else None —
    the one-liner a training script needs to pass
    ``fit(resume_from=...)`` only when there is something to resume
    (attempt 0 of an elastic run starts from scratch)."""
    from .checkpoint import format as _format
    for _step, path in reversed(_format.list_checkpoints(str(base))):
        if _format.probe_valid(path):
            return str(base)
    return None


def probe_world(env: Optional[dict] = None,
                timeout: float = 120.0) -> Optional[int]:
    """Re-probe the visible device set in a THROWAWAY subprocess (jax
    caches its backend for the life of a process — the supervisor must
    never bind one). Returns the device count, or None when the probe
    fails (backend wedged mid-preemption: the caller backs off and
    retries on the next attempt)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
            env=env if env is not None else os.environ.copy())
        if out.returncode == 0:
            return int(out.stdout.strip().splitlines()[-1])
    except (subprocess.SubprocessError, ValueError, IndexError, OSError):
        pass
    return None


def _with_device_count(flags: str, n: int) -> str:
    """XLA_FLAGS with the host-platform device count pinned to ``n``."""
    kept = [f for f in flags.split()
            if not f.startswith(_DEVCOUNT_FLAG + "=")]
    kept.append("%s=%d" % (_DEVCOUNT_FLAG, n))
    return " ".join(kept)


class Supervisor(object):
    """Run one training command elastically; see module docstring.

    Parameters
    ----------
    argv : list of str
        The child command. A leading ``*.py`` token is run with the
        current interpreter.
    max_restarts, backoff, backoff_max : optional
        Defaults from the ``MXNET_TPU_ELASTIC_*`` knobs.
    world_schedule : list of int, optional
        Virtual-mesh test rig: attempt ``i`` runs at
        ``schedule[min(i, len-1)]`` host devices (via ``XLA_FLAGS``).
        Without a schedule the device set is re-probed from the real
        backend between attempts (``probe_world``).
    jitter_seed : optional
        Seeds the backoff jitter for deterministic tests.
    """

    def __init__(self, argv: Sequence[str],
                 max_restarts: Optional[int] = None,
                 backoff: Optional[float] = None,
                 backoff_max: Optional[float] = None,
                 world_schedule: Optional[Sequence[int]] = None,
                 env: Optional[dict] = None,
                 jitter_seed: Optional[int] = None,
                 on_attempt: Optional[Callable[[int, dict], None]] = None):
        from . import config as _config
        argv = list(argv)
        if argv and argv[0].endswith(".py"):
            argv.insert(0, sys.executable)
        if not argv:
            raise ValueError("elastic supervisor needs a child command")
        self.argv = argv
        self.max_restarts = int(
            _config.get("MXNET_TPU_ELASTIC_MAX_RESTARTS")
            if max_restarts is None else max_restarts)
        self.backoff = float(_config.get("MXNET_TPU_ELASTIC_BACKOFF")
                             if backoff is None else backoff)
        self.backoff_max = float(
            _config.get("MXNET_TPU_ELASTIC_BACKOFF_MAX")
            if backoff_max is None else backoff_max)
        self.world_schedule = [int(w) for w in world_schedule] \
            if world_schedule else None
        self.env = dict(env) if env is not None else None
        self._rng = _pyrandom.Random(jitter_seed)
        self._on_attempt = on_attempt
        self.restarts = 0
        self.reshards = 0
        self._child: Optional[subprocess.Popen] = None
        self._terminated = False

    # ------------------------------------------------------------ signals
    def _install_forwarder(self):
        """Forward a SIGTERM aimed at the supervisor to the child (the
        scheduler preempts the whole allocation — the child must get its
        preemption notice) and stop restarting. Flag-set + os.kill only:
        anything allocation- or lock-heavy is unsafe in a handler."""
        if not hasattr(signal, "SIGTERM"):
            return None
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _handler(_signum, _frame):
                self._terminated = True
                child = self._child
                if child is not None:
                    try:
                        os.kill(child.pid, signal.SIGTERM)
                    except OSError:
                        pass        # already gone



            signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError):
            return None         # not the main thread

        def _restore():
            try:
                signal.signal(signal.SIGTERM, prev)
            except (ValueError, OSError, TypeError):
                pass

        return _restore

    def _backoff_sleep(self, delay: float) -> None:
        """Backoff that a between-attempts SIGTERM can cut short: PEP 475
        resumes one long ``time.sleep`` after the flag-only handler
        returns, so sleep in small slices and re-check the flag (an
        Event would be cleaner but ``Event.set`` takes a lock — the
        signal-unsafe hazard class the repo lint rejects)."""
        deadline = time.monotonic() + max(0.0, delay)
        while not self._terminated:
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.25, left))

    # ------------------------------------------------------------- world
    def _world_for_attempt(self, attempt: int) -> Optional[int]:
        if self.world_schedule:
            i = min(attempt, len(self.world_schedule) - 1)
            return self.world_schedule[i]
        return None

    def _env_for_attempt(self, attempt: int, world: Optional[int]) -> dict:
        env = dict(self.env if self.env is not None else os.environ)
        env["MXNET_TPU_ELASTIC_ATTEMPT"] = str(attempt)
        if attempt > 0:
            env["MXNET_TPU_ELASTIC_RESUMED"] = "1"
        if world is not None:
            env["XLA_FLAGS"] = _with_device_count(
                env.get("XLA_FLAGS", ""), world)
        return env

    # -------------------------------------------------------------- run
    def run(self) -> int:
        from . import profiler as _profiler
        restore_sig = self._install_forwarder()
        attempt = 0
        prev_world: Optional[int] = None
        try:
            while True:
                if self._terminated:
                    # the preemption landed BETWEEN attempts (backoff
                    # sleep / world probe): do not spawn a fresh child
                    # just to have the platform hard-kill it
                    log.warning("elastic: supervisor was SIGTERMed "
                                "between attempts; not restarting")
                    return 143
                world = self._world_for_attempt(attempt)
                env = self._env_for_attempt(attempt, world)
                if world is None:
                    # real backend: ask a throwaway process what is
                    # actually visible right now (logging + reshard
                    # accounting; the child binds whatever it sees)
                    world = probe_world(env)
                if world is not None:
                    _profiler.set_gauge("elastic_world", world)
                if attempt > 0 and world is not None \
                        and prev_world is not None and world != prev_world:
                    self.reshards += 1
                    _profiler.incr_counter("elastic_reshard")
                    log.warning("elastic: world size %d -> %d; the child "
                                "will reshard-on-load", prev_world, world)
                prev_world = world if world is not None else prev_world
                if self._on_attempt is not None:
                    self._on_attempt(attempt, env)
                log.info("elastic attempt %d (world=%s): %s",
                         attempt, world, " ".join(self.argv))
                self._child = subprocess.Popen(self.argv, env=env)
                rc = self._child.wait()
                self._child = None
                if rc == 0:
                    return 0
                if self._terminated:
                    # the preemption was aimed at US — do not restart,
                    # propagate the conventional status
                    log.warning("elastic: supervisor was SIGTERMed; "
                                "child exited %d; not restarting", rc)
                    return 143
                if rc == 143:
                    _profiler.incr_counter("elastic_preempt")
                    log.warning("elastic: child preempted (exit 143)")
                else:
                    _profiler.incr_counter("elastic_crash")
                    log.warning("elastic: child died (%s)",
                                "signal %d" % -rc if rc < 0
                                else "exit %d" % rc)
                if self.restarts >= self.max_restarts:
                    log.error("elastic: restart budget exhausted "
                              "(%d); giving up with rc=%d",
                              self.max_restarts, rc)
                    return rc if rc != 0 else 1
                self.restarts += 1
                _profiler.incr_counter("elastic_restart")
                delay = min(self.backoff_max,
                            self.backoff * (2 ** (self.restarts - 1)))
                delay *= 1.0 + 0.25 * self._rng.random()
                log.info("elastic: restart %d/%d in %.2fs",
                         self.restarts, self.max_restarts, delay)
                self._backoff_sleep(delay)
                attempt += 1
        finally:
            if restore_sig is not None:
                restore_sig()


def supervise(argv: Sequence[str], **kwargs) -> int:
    """One-call form: build a :class:`Supervisor` and run it."""
    return Supervisor(argv, **kwargs).run()


def _parse_schedule(s: str) -> List[int]:
    parts = [p for p in re.split(r"[,x\s]+", s.strip()) if p]
    sched = [int(p) for p in parts]
    if not sched or any(w < 1 for w in sched):
        raise argparse.ArgumentTypeError(
            "--world-schedule wants positive device counts, e.g. 8,4,2")
    return sched


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.elastic",
        description="preemption-aware elastic training supervisor: runs "
                    "a training command in a child process, restarts it "
                    "on preemption (exit 143) or crash at the current "
                    "device-set size, bounded with backoff")
    parser.add_argument("--max-restarts", type=int, default=None)
    parser.add_argument("--backoff", type=float, default=None,
                        help="base seconds of the exponential backoff")
    parser.add_argument("--backoff-max", type=float, default=None)
    parser.add_argument("--world-schedule", type=_parse_schedule,
                        default=None,
                        help="test rig: host device count per attempt, "
                             "e.g. 8,4,2 (last entry repeats)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="child command (prefix with -- to separate)")
    args = parser.parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no child command given")
    logging.basicConfig(level=logging.INFO,
                        format="[elastic] %(message)s")
    return supervise(command, max_restarts=args.max_restarts,
                     backoff=args.backoff, backoff_max=args.backoff_max,
                     world_schedule=args.world_schedule)


if __name__ == "__main__":
    sys.exit(main())

"""Preemption-aware elastic training supervisor (ROADMAP item 4).

``fit`` already survives a preemption NOTICE: SIGTERM lands a final
synchronous checkpoint and exits 143 (PR 5). This module supplies the
missing half — the thing that *re-enters* training after the preemption:

* run the training program in a CHILD process (a supervisor that shares
  the training process dies with it — only a process boundary survives
  ``kill -9``);
* treat exit 143 (clean preemption) and any crash (signal death,
  non-zero exit) as a restartable event, bounded by
  ``MXNET_TPU_ELASTIC_MAX_RESTARTS`` with exponential backoff + jitter;
* re-probe the visible device set between attempts and re-launch the
  child at the NEW world size (on preemptible capacity the replacement
  slice is routinely smaller or larger than the one that died);
* the child resumes from the newest valid checkpoint
  (``resume_dir(base)``) — reshard-on-load re-lays every array out onto
  whatever mesh the new world size builds, so an 8-chip checkpoint
  restores onto 4 chips, 2, or 1 (``docs/architecture/elastic.md``).

The supervisor itself is deliberately framework-light: this module
touches only stdlib + the config/profiler modules, and the supervisor
process must never INITIALIZE a jax backend (``python -m`` necessarily
imports the package, which imports the jax library — but a backend pins
its device view for the life of the process, so every device query runs
in a throwaway subprocess instead: :func:`probe_world`). A regression
test runs the supervisor under an unresolvable ``JAX_PLATFORMS`` so any
in-process backend initialization fails loudly.

CLI::

    python -m mxnet_tpu.elastic [--max-restarts N] [--backoff S]
        [--world-schedule 8,4,2] -- python train.py --my-args

Multi-host pod mode (ISSUE 11)::

    tools/launch.py -n N --coordinated -- python train.py ...
    # == every host runs: python -m mxnet_tpu.elastic --coordinated -- ...

Each host runs ONE :class:`PodCoordinator` (rank/world from the same
DMLC_* env the launcher sets). The coordinators form the pod's control
plane over a tiny RE-HOSTABLE KV service (``dist.PodKVServer`` — the
reference's ps-lite scheduler was its own process too; a
``jax.distributed`` client is NOT survivable here, see
``parallel/dist.py``), hosted by the current LEADER — the lowest live
rank, rank 0 at bootstrap. Every coordinator publishes liveness
heartbeats (``dist.heartbeat_start``): a host that dies (SIGKILL) or
freezes whole (SIGSTOP — a stuck machine) stops beating and is caught
by the ``MXNET_KVSTORE_HEARTBEAT_STALE_SECS`` deadline. On a death the
survivors DRAIN (SIGTERM the child, escalate to SIGKILL after
``MXNET_TPU_ELASTIC_DRAIN_GRACE``), re-rendezvous at the surviving
world size (generation bump; the leader publishes membership — each
member's host, probe-ring port and fail-over port — plus a fresh
data-plane coordinator port), and relaunch: the children resume from
the newest COMPLETE checkpoint, resharding onto the new world. A
training CHILD failing with its supervisor alive (crash, preemption,
or — with the opt-in ``MXNET_TPU_ELASTIC_STALL_SECS`` watchdog — a
wedged child) triggers a POD-WIDE restart at the unchanged membership
instead: bulk-synchronous SPMD cannot restart one rank alone, and a
child-level stall is symmetric across the pod (every peer blocks in
the same collective), so eviction would be wrong.

LEADER FAIL-OVER (ISSUE 12): when the control plane itself goes dark —
the leader's host died, or only its KV service did — every survivor's
``dead_ranks`` reports EVERY member unreadable. That is ambiguous
("the leader is dead" vs "I am partitioned"), so the survivors
adjudicate over the peer-to-peer PROBE RING (``dist.ProbeRing``; the
addresses came from the generation's membership record, no control
plane needed): live + positively-refused peers are accounted, and when
the live set is a majority of the unaccounted-excluded membership the
pod recovers IN PLACE — the lowest live rank is elected
(``dist.elect_leader``), re-hosts the KV service on its published
fail-over port, every survivor re-points its client, and the next
generation rendezvous proceeds as after any other host death. Only a
true minority partition drains and exits 1 for a cluster-manager job
restart. Counters: ``elastic_dead_host``, ``elastic_reshard``,
``elastic_restart``, ``elastic_stall``, ``elastic_leader_failover``;
gauges ``elastic_world``, ``elastic_leader`` (the current leader's
original pod rank).

Environment exported to every attempt:

* ``MXNET_TPU_ELASTIC_ATTEMPT`` — 0-based attempt index (the training
  script can key per-attempt behavior on it; the fault drills do);
* ``MXNET_TPU_ELASTIC_RESUMED=1`` — on every attempt after the first;
* with a world schedule (the virtual-mesh test rig), ``XLA_FLAGS`` is
  rewritten with ``--xla_force_host_platform_device_count=<n>`` so the
  child binds at the scheduled world size.

Counters: ``elastic_restart`` (every re-entry), ``elastic_preempt``
(exit-143 children), ``elastic_crash`` (signal/non-zero children),
``elastic_reshard`` (re-entries whose world size changed) and the
``elastic_world`` gauge.
"""
from __future__ import annotations

import argparse
import logging
import os
import random as _pyrandom
import re
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

__all__ = ["Supervisor", "PodCoordinator", "supervise", "resume_dir",
           "probe_world", "backoff_delay", "main"]

log = logging.getLogger(__name__)


def backoff_delay(restarts: int, backoff: float, backoff_max: float,
                  rng=None) -> float:
    """Bounded-exponential respawn delay before the Nth restart
    (1-based): ``min(backoff_max, backoff * 2**(restarts-1))``, plus up
    to 25% jitter when ``rng`` (a ``random.Random``) is given. The one
    formula every supervisor in the tree uses — the training supervisor
    below and the fleet's per-replica supervisors
    (``mxnet_tpu.fleet.gateway``) — so a drill can bound worst-case
    recovery time from the knobs alone."""
    delay = min(float(backoff_max),
                float(backoff) * (2 ** (max(1, int(restarts)) - 1)))
    if rng is not None:
        delay *= 1.0 + 0.25 * rng.random()
    return delay


def _blackbox():
    """The flight-recorder gate (one implementation:
    ``profiler.blackbox`` — zero-import when the knob is off).
    Coordinator transitions (rendezvous, election, fail-over, drain,
    stall) are exactly the events a post-mortem needs and exactly the
    ones that die with the process, so they go through here."""
    from . import profiler as _profiler
    return _profiler.blackbox()

_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count"


def resume_dir(base: str) -> Optional[str]:
    """``base`` if it holds at least one VALID checkpoint, else None —
    the one-liner a training script needs to pass
    ``fit(resume_from=...)`` only when there is something to resume
    (attempt 0 of an elastic run starts from scratch).

    Orphaned pod staging dirs are audited first
    (``finalize_staged_pod_saves``): a save whose original leader died
    between shard-record publication and manifest commit is finalized
    by the resuming generation — or provably left for GC — BEFORE the
    newest-checkpoint decision, so the pod never resumes older work
    than it durably has."""
    from .checkpoint import format as _format
    try:
        _format.finalize_staged_pod_saves(
            str(base), by_rank=int(os.environ.get("DMLC_WORKER_ID", "0")))
    except Exception:                                      # noqa: BLE001
        log.warning("resume_dir: pod staging audit failed; resuming "
                    "from the newest committed checkpoint", exc_info=True)
    for _step, path in reversed(_format.list_checkpoints(str(base))):
        if _format.probe_valid(path):
            return str(base)
    return None


def probe_world(env: Optional[dict] = None,
                timeout: float = 120.0) -> Optional[int]:
    """Re-probe the visible device set in a THROWAWAY subprocess (jax
    caches its backend for the life of a process — the supervisor must
    never bind one). Returns the device count, or None when the probe
    fails (backend wedged mid-preemption: the caller backs off and
    retries on the next attempt)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
            env=env if env is not None else os.environ.copy())
        if out.returncode == 0:
            return int(out.stdout.strip().splitlines()[-1])
    except (subprocess.SubprocessError, ValueError, IndexError, OSError):
        pass
    return None


def _with_device_count(flags: str, n: int) -> str:
    """XLA_FLAGS with the host-platform device count pinned to ``n``."""
    kept = [f for f in flags.split()
            if not f.startswith(_DEVCOUNT_FLAG + "=")]
    kept.append("%s=%d" % (_DEVCOUNT_FLAG, n))
    return " ".join(kept)


class Supervisor(object):
    """Run one training command elastically; see module docstring.

    Parameters
    ----------
    argv : list of str
        The child command. A leading ``*.py`` token is run with the
        current interpreter.
    max_restarts, backoff, backoff_max : optional
        Defaults from the ``MXNET_TPU_ELASTIC_*`` knobs.
    world_schedule : list of int, optional
        Virtual-mesh test rig: attempt ``i`` runs at
        ``schedule[min(i, len-1)]`` host devices (via ``XLA_FLAGS``).
        Without a schedule the device set is re-probed from the real
        backend between attempts (``probe_world``).
    jitter_seed : optional
        Seeds the backoff jitter for deterministic tests.
    """

    def __init__(self, argv: Sequence[str],
                 max_restarts: Optional[int] = None,
                 backoff: Optional[float] = None,
                 backoff_max: Optional[float] = None,
                 world_schedule: Optional[Sequence[int]] = None,
                 env: Optional[dict] = None,
                 jitter_seed: Optional[int] = None,
                 on_attempt: Optional[Callable[[int, dict], None]] = None):
        from . import config as _config
        argv = list(argv)
        if argv and argv[0].endswith(".py"):
            argv.insert(0, sys.executable)
        if not argv:
            raise ValueError("elastic supervisor needs a child command")
        self.argv = argv
        self.max_restarts = int(
            _config.get("MXNET_TPU_ELASTIC_MAX_RESTARTS")
            if max_restarts is None else max_restarts)
        self.backoff = float(_config.get("MXNET_TPU_ELASTIC_BACKOFF")
                             if backoff is None else backoff)
        self.backoff_max = float(
            _config.get("MXNET_TPU_ELASTIC_BACKOFF_MAX")
            if backoff_max is None else backoff_max)
        self.world_schedule = [int(w) for w in world_schedule] \
            if world_schedule else None
        self.env = dict(env) if env is not None else None
        self._rng = _pyrandom.Random(jitter_seed)
        self._on_attempt = on_attempt
        self.restarts = 0
        self.reshards = 0
        self._child: Optional[subprocess.Popen] = None
        self._terminated = False

    # ------------------------------------------------------------ signals
    def _install_forwarder(self):
        """Forward a SIGTERM aimed at the supervisor to the child (the
        scheduler preempts the whole allocation — the child must get its
        preemption notice) and stop restarting. Flag-set + os.kill only:
        anything allocation- or lock-heavy is unsafe in a handler."""
        if not hasattr(signal, "SIGTERM"):
            return None
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _handler(_signum, _frame):
                self._terminated = True
                child = self._child
                if child is not None:
                    try:
                        os.kill(child.pid, signal.SIGTERM)
                    except OSError:
                        pass        # already gone



            signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError):
            return None         # not the main thread

        def _restore():
            try:
                signal.signal(signal.SIGTERM, prev)
            except (ValueError, OSError, TypeError):
                pass

        return _restore

    def _backoff_sleep(self, delay: float) -> None:
        """Backoff that a between-attempts SIGTERM can cut short: PEP 475
        resumes one long ``time.sleep`` after the flag-only handler
        returns, so sleep in small slices and re-check the flag (an
        Event would be cleaner but ``Event.set`` takes a lock — the
        signal-unsafe hazard class the repo lint rejects)."""
        deadline = time.monotonic() + max(0.0, delay)
        while not self._terminated:
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.25, left))

    # ------------------------------------------------------------- world
    def _world_for_attempt(self, attempt: int) -> Optional[int]:
        if self.world_schedule:
            i = min(attempt, len(self.world_schedule) - 1)
            return self.world_schedule[i]
        return None

    def _env_for_attempt(self, attempt: int, world: Optional[int]) -> dict:
        env = dict(self.env if self.env is not None else os.environ)
        env["MXNET_TPU_ELASTIC_ATTEMPT"] = str(attempt)
        if attempt > 0:
            env["MXNET_TPU_ELASTIC_RESUMED"] = "1"
        if world is not None:
            env["XLA_FLAGS"] = _with_device_count(
                env.get("XLA_FLAGS", ""), world)
        return env

    # -------------------------------------------------------------- run
    def run(self) -> int:
        from . import profiler as _profiler
        restore_sig = self._install_forwarder()
        attempt = 0
        prev_world: Optional[int] = None
        try:
            while True:
                if self._terminated:
                    # the preemption landed BETWEEN attempts (backoff
                    # sleep / world probe): do not spawn a fresh child
                    # just to have the platform hard-kill it
                    log.warning("elastic: supervisor was SIGTERMed "
                                "between attempts; not restarting")
                    return 143
                world = self._world_for_attempt(attempt)
                env = self._env_for_attempt(attempt, world)
                if world is None:
                    # real backend: ask a throwaway process what is
                    # actually visible right now (logging + reshard
                    # accounting; the child binds whatever it sees)
                    world = probe_world(env)
                if world is not None:
                    _profiler.set_gauge("elastic_world", world)
                if attempt > 0 and world is not None \
                        and prev_world is not None and world != prev_world:
                    self.reshards += 1
                    _profiler.incr_counter("elastic_reshard")
                    log.warning("elastic: world size %d -> %d; the child "
                                "will reshard-on-load", prev_world, world)
                prev_world = world if world is not None else prev_world
                if self._on_attempt is not None:
                    self._on_attempt(attempt, env)
                log.info("elastic attempt %d (world=%s): %s",
                         attempt, world, " ".join(self.argv))
                self._child = subprocess.Popen(self.argv, env=env)
                rc = self._child.wait()
                self._child = None
                if rc == 0:
                    return 0
                if self._terminated:
                    # the preemption was aimed at US — do not restart,
                    # propagate the conventional status
                    log.warning("elastic: supervisor was SIGTERMed; "
                                "child exited %d; not restarting", rc)
                    return 143
                if rc == 143:
                    _profiler.incr_counter("elastic_preempt")
                    log.warning("elastic: child preempted (exit 143)")
                else:
                    _profiler.incr_counter("elastic_crash")
                    log.warning("elastic: child died (%s)",
                                "signal %d" % -rc if rc < 0
                                else "exit %d" % rc)
                if self.restarts >= self.max_restarts:
                    log.error("elastic: restart budget exhausted "
                              "(%d); giving up with rc=%d",
                              self.max_restarts, rc)
                    return rc if rc != 0 else 1
                self.restarts += 1
                _profiler.incr_counter("elastic_restart")
                delay = backoff_delay(self.restarts, self.backoff,
                                      self.backoff_max, rng=self._rng)
                log.info("elastic: restart %d/%d in %.2fs",
                         self.restarts, self.max_restarts, delay)
                self._backoff_sleep(delay)
                attempt += 1
        finally:
            if restore_sig is not None:
                restore_sig()


# exit status of a coordinator that judged its OWN host dead (wedged
# child): the host cannot trust itself, so it leaves the pod and lets
# the cluster manager replace the machine (EX_TEMPFAIL)
SELF_DEAD_RC = 75


class PodCoordinator(object):
    """Per-host pod supervisor (``--coordinated``; module docstring).

    One coordinator runs on every host. Control plane: the re-hostable
    ``dist.PodKVServer`` on the DMLC coordinator address, hosted by the
    current leader (lowest live rank; no jax backend — nor even a jax
    coordination client — ever exists in this process). Liveness: plain
    heartbeats that freeze exactly when this process does. A dead or
    frozen host triggers pod-wide drain → rendezvous at the surviving
    world → relaunch, with the children resuming from the newest
    complete checkpoint (reshard-on-load); a child-level failure
    triggers a pod-wide restart at the unchanged membership; the
    LEADER's death triggers probe-ring adjudication and a control-plane
    re-host on the elected successor's fail-over port.
    """

    def __init__(self, argv: Sequence[str],
                 max_restarts: Optional[int] = None,
                 heartbeat_period: Optional[float] = None,
                 stale_after: Optional[float] = None,
                 stall_after: Optional[float] = None,
                 drain_grace: Optional[float] = None,
                 rendezvous_window: Optional[float] = None,
                 env: Optional[dict] = None,
                 advertise_host: Optional[str] = None):
        from . import config as _config
        from .parallel import dist as _dist
        argv = list(argv)
        if argv and argv[0].endswith(".py"):
            argv.insert(0, sys.executable)
        if not argv:
            raise ValueError("pod coordinator needs a child command")
        self.argv = argv
        cluster = _dist.cluster_env()
        if cluster is None:
            raise RuntimeError(
                "--coordinated needs the launcher env: run every host "
                "through tools/launch.py -n N --coordinated (sets "
                "DMLC_PS_ROOT_URI/PORT, DMLC_NUM_WORKER, DMLC_WORKER_ID)")
        self.rank = cluster["rank"]
        self.world = cluster["num_workers"]
        self.coordinator = cluster["coordinator"]
        self.max_restarts = int(
            _config.get("MXNET_TPU_ELASTIC_MAX_RESTARTS")
            if max_restarts is None else max_restarts)
        self.heartbeat_period = float(
            _config.get("MXNET_TPU_HEARTBEAT_PERIOD")
            if heartbeat_period is None else heartbeat_period)
        self.stale_after = float(
            _config.get("MXNET_KVSTORE_HEARTBEAT_STALE_SECS")
            if stale_after is None else stale_after)
        self.stall_after = float(
            _config.get("MXNET_TPU_ELASTIC_STALL_SECS")
            if stall_after is None else stall_after)
        self.drain_grace = float(
            _config.get("MXNET_TPU_ELASTIC_DRAIN_GRACE")
            if drain_grace is None else drain_grace)
        self.rendezvous_window = float(
            max(2.0 * self.stale_after, 10.0)
            if rendezvous_window is None else rendezvous_window)
        self.bootstrap_timeout = float(_config.get("MXNET_TPU_DIST_TIMEOUT"))
        self.env = dict(env) if env is not None else None
        if advertise_host is None:
            advertise_host = os.environ.get("MXNET_TPU_POD_HOST")
        if advertise_host is None:
            if self.rank == 0:
                advertise_host = self.coordinator.rsplit(":", 1)[0]
            else:
                import socket
                advertise_host = socket.gethostname()
        self.advertise = advertise_host
        self.restarts = 0
        self.reshards = 0
        self.dead_hosts = 0
        self.leader_failovers = 0
        # current pod membership (ORIGINAL ranks — stable identity across
        # control-plane re-hostings), the latest generation's per-member
        # info (host, probe-ring port, fail-over port), and the current
        # leader (= the control-plane host)
        self.members: List[int] = list(range(self.world))
        self.peer_info: dict = {}
        self.leader = 0
        self.cp_addr = self.coordinator
        self.clock_offset = 0.0
        self._kv_server = None
        self._kv_client = None
        self._ring = None
        self._bb = None
        self._metrics = None
        self._straggler_refresh = 0.0
        self._failover_live: Optional[List[int]] = None
        self._coordsvc_kill = False
        self._child: Optional[subprocess.Popen] = None
        self._terminated = False
        self._progress_path: Optional[str] = None
        self._workdir: Optional[str] = None
        self._gen = 0

    # ------------------------------------------------------------ liveness
    def _dead_peers(self, members) -> List[int]:
        from .parallel import dist as _dist
        dead = _dist.dead_ranks(stale_after=self.stale_after,
                                timeout_ms=1000, ranks=list(members))
        return [r for r in dead if r in members]

    def _failover_port(self) -> int:
        """The TCP port THIS host would re-host the control plane on if
        elected (published in every generation's join record). A fresh
        free port per generation by default; the
        ``MXNET_TPU_FAILOVER_PORT`` knob pins it (production: a port the
        window between publication and use cannot leak away)."""
        from . import config as _config
        port = int(_config.get("MXNET_TPU_FAILOVER_PORT"))
        if port > 0:
            return port
        from .parallel import dist as _dist
        return _dist.free_port()

    def _probe_statuses(self, members) -> dict:
        """Probe every member's ring (bounded attempts; any 'live'
        answer wins): rank -> live | dead | unreachable."""
        from . import config as _config
        from .parallel import dist as _dist
        attempts = max(1, int(_config.get("MXNET_TPU_PROBE_ATTEMPTS")))
        statuses = {}
        for r in members:
            if r == self.rank:
                statuses[r] = "live"
                continue
            info = self.peer_info.get(r) or {}
            addr = "%s:%s" % (info.get("host", ""), info.get("probe", 0))
            status = "unreachable"
            for _ in range(attempts):
                status = _dist.probe_peer(addr)
                if status == "live":
                    break
                time.sleep(0.1)
            statuses[r] = status
        return statuses

    def _adjudicate(self, members) -> str:
        """The control plane is unreachable (every member's heartbeat
        unreadable, ourselves included). That conflates two very
        different situations — "the leader's host died" and "I am the
        one partitioned" — so adjudicate over the probe ring, which
        needs no control plane: positively-refused peers (the host's
        TCP stack answered, the coordinator is gone) are CONFIRMED
        dead and excluded from the electorate; a live MAJORITY of the
        rest recovers in place (``"leader-lost"`` → fail-over), and
        anything less means this side of a partition must exit for a
        job restart (``"control-plane-lost"``)."""
        statuses = self._probe_statuses(members)
        live = sorted(r for r, s in statuses.items() if s == "live")
        confirmed_dead = sorted(r for r, s in statuses.items()
                                if s == "dead")
        electorate = len(members) - len(confirmed_dead)
        log.warning("pod: control plane unreachable; probe ring says "
                    "live=%s confirmed-dead=%s unreachable=%s",
                    live, confirmed_dead,
                    sorted(r for r, s in statuses.items()
                           if s == "unreachable"))
        if 2 * len(live) > electorate:
            self._failover_live = live
            log.warning("pod: healthy majority (%d of %d accountable) — "
                        "electing a new leader and re-hosting the "
                        "control plane", len(live), electorate)
            return "leader-lost"
        log.error("pod: only %d of %d accountable members reachable — "
                  "this host is on the minority side of a partition; "
                  "draining and exiting for a job restart",
                  len(live), electorate)
        return "control-plane-lost"

    def _start_metrics(self):
        """Opt-in coordinator ``/metrics`` (``MXNET_TPU_OBS_METRICS_PORT``,
        same knob the serve endpoint honors; -1 = off). A port conflict
        — e.g. several drill coordinators on one machine with a fixed
        port — degrades to no-endpoint with a warning, never a dead
        supervisor."""
        from . import config as _config
        from . import profiler as _profiler
        try:
            port = int(_config.get("MXNET_TPU_OBS_METRICS_PORT"))
        except (TypeError, ValueError):
            return None
        if port < 0:
            return None
        try:
            from .obs.http import MetricsServer
            srv = MetricsServer(port=port)
        except OSError as exc:
            _profiler.incr_counter("elastic_metrics_bind_failed")
            log.warning("pod: /metrics endpoint could not bind port %d "
                        "(%s); continuing without one", port, exc)
            return None
        log.info("pod: coordinator /metrics at %s", srv.url)
        return srv

    def _sync_clock(self) -> None:
        """Estimate this host's wall-clock offset vs the control-plane
        host (PodKV CLOCK exchange, min-RTT sample) for the flight
        recorder's cross-host alignment; exported to the child via
        ``MXNET_TPU_OBS_CLOCK_OFFSET``. Only runs when the recorder is
        armed — the exchange is telemetry, not control."""
        if self._bb is None:
            return
        off = 0.0
        if self.rank != self.leader and self._kv_client is not None:
            try:
                off = self._kv_client.clock_offset() or 0.0
            except Exception:                              # noqa: BLE001
                off = 0.0
        self.clock_offset = off
        self._bb.set_clock_offset(off)

    def _refresh_straggler_gauges(self, members) -> None:
        """Leader-side: refresh the per-rank straggler gauges the
        ``/metrics`` endpoint exposes, from the step windows the
        training children publish to the control-plane KV. Bounded to
        one sweep per ~2s and gated on the endpoint being up."""
        from . import config as _config
        if self._metrics is None or self.rank != self.leader:
            return
        now = time.monotonic()
        if now - self._straggler_refresh < 2.0:
            return
        self._straggler_refresh = now
        if float(_config.get("MXNET_TPU_OBS_STRAGGLER_RATIO")) <= 0:
            return
        try:
            from .obs import straggler as _straggler
            _straggler.refresh_gauges(len(members), gen=self._gen)
        except Exception:                                  # noqa: BLE001
            pass    # telemetry must never destabilize the monitor

    def _kill_control_plane(self) -> None:
        """The ``coordsvc`` fault kind (split-brain drill): abruptly
        stop the control-plane KV service this coordinator hosts while
        the host — and the training child — stay up."""
        if self._kv_server is not None:
            log.warning("pod: coordsvc fault — abruptly stopping the "
                        "hosted control-plane KV service (host stays up)")
            self._kv_server.stop()
            self._kv_server = None
        else:
            log.warning("pod: coordsvc fault delivered to a coordinator "
                        "hosting no control-plane service; ignored")

    def _failover(self) -> bool:
        """Re-host the control plane after a leader loss: elect the
        lowest live rank (every survivor computes the same answer from
        the same generation record — no communication needed, and none
        available), bind its published fail-over port, re-point every
        client, restart heartbeats. Returns False when the re-host
        cannot complete (the caller exits for a job restart)."""
        from . import profiler as _profiler
        from .parallel import dist as _dist
        live = self._failover_live or [self.rank]
        self._failover_live = None
        survivors = sorted(live)
        leader = _dist.elect_leader(survivors)
        info = self.peer_info.get(leader) or {}
        port = int(info.get("failover") or 0)
        host = info.get("host") or "127.0.0.1"
        if not port:
            log.error("pod: rank %d published no fail-over port; cannot "
                      "re-host the control plane", leader)
            return False
        addr = "%s:%d" % (host, port)
        if self._bb is not None:
            self._bb.record("pod", "elect", leader=leader,
                            survivors=survivors, addr=addr)
        _dist.heartbeat_stop()
        _dist.reset_liveness()
        if self._kv_server is not None:     # old control plane, if ours
            self._kv_server.stop()
            self._kv_server = None
        if leader == self.rank:
            try:
                self._kv_server = _dist.PodKVServer(port=port)
            except OSError as exc:
                log.error("pod: elected leader could not bind the "
                          "fail-over port %s: %s", addr, exc)
                return False
        self._kv_client = _dist.PodKVClient(addr)
        if not self._kv_client.ping(self.bootstrap_timeout):
            log.error("pod: the re-hosted control plane at %s never "
                      "answered within %.0fs (the elected leader died "
                      "mid-fail-over?)", addr, self.bootstrap_timeout)
            return False
        _dist.set_kv_backend(self._kv_client)
        _dist.heartbeat_start(period=self.heartbeat_period,
                              as_rank=self.rank)
        self.members = survivors
        self.leader = leader
        self.cp_addr = addr
        self.leader_failovers += 1
        _profiler.incr_counter("elastic_leader_failover")
        _profiler.set_gauge("elastic_leader", leader)
        log.warning("pod: control plane re-hosted on rank %d (%s); "
                    "surviving members %s", leader, addr, survivors)
        if self._bb is not None:
            self._bb.record("pod", "failover", leader=leader, addr=addr,
                            survivors=survivors)
            self._bb.flush("failover")
        return True

    # ---------------------------------------------------------- rendezvous
    def _rendezvous(self, gen: int) -> Optional[dict]:
        """Agree on generation ``gen``'s membership. Every live
        coordinator publishes a join key carrying its host, probe-ring
        port and fail-over port; the leader (lowest live member)
        collects joins within the rendezvous window and publishes the
        member list, the per-member info map (what a later fail-over
        election runs on) and a fresh data-plane coordinator port;
        followers wait for that record (bounded). Returns the record,
        or None when this rank was judged dead and evicted."""
        import json
        from . import profiler as _profiler
        from .parallel import dist as _dist
        join = {"host": self.advertise,
                "probe": self._ring.port if self._ring is not None else 0,
                "failover": self._failover_port()}
        _dist.kv_set("mxpod/g%d/join/%d" % (gen, self.rank),
                     json.dumps(join))
        dead = set()
        if gen > 0:
            dead = set(self._dead_peers(self.members))
            dead.discard(self.rank)   # we are here, deciding to continue
        candidates = [r for r in self.members if r not in dead]
        leader = _dist.elect_leader(candidates)
        key = "mxpod/g%d/members" % gen
        if leader == self.rank:
            members, peers = [], {}
            deadline = time.monotonic() + (
                self.bootstrap_timeout if gen == 0
                else self.rendezvous_window)
            for r in candidates:
                left_ms = max(1, int((deadline - time.monotonic()) * 1000))
                raw = _dist.kv_get("mxpod/g%d/join/%d" % (gen, r), left_ms)
                if raw is not None:
                    members.append(r)
                    try:
                        peers[str(r)] = json.loads(raw)
                    except ValueError:
                        peers[str(r)] = {}
                elif gen == 0:
                    raise RuntimeError(
                        "pod rendezvous: rank %d of %d never joined "
                        "generation 0 within %.0fs — check that every "
                        "host launched its coordinator"
                        % (r, self.world, self.bootstrap_timeout))
                else:
                    log.warning("pod: rank %d missed the generation-%d "
                                "rendezvous window; continuing without "
                                "it", r, gen)
            rec = {"gen": gen, "ranks": members, "leader": self.rank,
                   "peers": peers,
                   "coordinator": "%s:%d" % (self.advertise,
                                             _dist.free_port())}
            _dist.kv_set(key, json.dumps(rec))
        else:
            # a follower must outwait the leader's WORST case: the full
            # collection window plus the bootstrap allowance (a follower
            # timing out on the same clock as a still-collecting leader
            # would drop a healthy host out of a recoverable pod)
            wait = self.bootstrap_timeout + self.rendezvous_window
            raw = _dist.kv_get(key, int(wait * 1000))
            if raw is None:
                raise RuntimeError(
                    "pod rendezvous: the leader never published "
                    "generation-%d membership within %.0fs (leader "
                    "host dead mid-rendezvous? the monitor adjudicates "
                    "over the probe ring)" % (gen, wait))
            rec = json.loads(raw)
        # every member learns the full membership + data-plane info here:
        # a later fail-over election needs no control plane at all
        self.peer_info = {int(r): info
                          for r, info in (rec.get("peers") or {}).items()}
        self.leader = int(rec.get("leader", min(rec["ranks"])))
        _profiler.set_gauge("elastic_leader", self.leader)
        if self.rank not in rec["ranks"]:
            return None                           # judged dead: evicted
        self.members = list(rec["ranks"])
        return rec

    # --------------------------------------------------------------- child
    def _child_env(self, gen: int, rec: dict) -> dict:
        env = dict(self.env if self.env is not None else os.environ)
        members = rec["ranks"]
        uri, _, port = rec["coordinator"].rpartition(":")
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": uri,
            "DMLC_PS_ROOT_PORT": port,
            "DMLC_NUM_WORKER": str(len(members)),
            "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(members.index(self.rank)),
            "MXNET_TPU_POD_GEN": str(gen),
            "MXNET_TPU_ELASTIC_COORDINATED": "1",
            "MXNET_TPU_ELASTIC_ATTEMPT": str(gen),
            "MXNET_TPU_ELASTIC_PROGRESS_FILE": self._progress_path,
            # pod observability plumbing: the child's ORIGINAL pod rank
            # (flight-recorder file naming — stable across generations),
            # the control-plane KV address (straggler step windows
            # publish there, readable by the supervisor and surviving
            # child restarts), and this host's wall-clock offset vs the
            # control plane (cross-host timeline alignment)
            "MXNET_TPU_POD_RANK": str(self.rank),
            "MXNET_TPU_POD_KV": self.cp_addr,
            "MXNET_TPU_OBS_CLOCK_OFFSET": repr(self.clock_offset),
        })
        if gen > 0:
            env["MXNET_TPU_ELASTIC_RESUMED"] = "1"
        return env

    def _drain_child(self) -> None:
        """Pod drain: preemption-notice SIGTERM first (the child lands a
        best-effort final save and exits 143), SIGKILL after the grace —
        a child wedged inside a collective whose peer died cannot
        observe the notice."""
        child = self._child
        if child is None or child.poll() is not None:
            return
        try:
            child.send_signal(signal.SIGTERM)
        except OSError:
            return
        try:
            child.wait(timeout=self.drain_grace)
        except subprocess.TimeoutExpired:
            log.warning("pod drain: child ignored SIGTERM for %.0fs "
                        "(wedged collective?); escalating to SIGKILL",
                        self.drain_grace)
            try:
                child.kill()
            except OSError:
                pass
            child.wait()

    # ----------------------------------------------------------------- run
    def run(self) -> int:
        import tempfile
        from . import profiler as _profiler
        from .parallel import dist as _dist
        # control plane: OUR re-hostable KV service, not a jax
        # coordination client (which LOG(FATAL)s the process when its
        # service dies — the exact event fail-over survives; see
        # parallel/dist.py). The gen-0 leader binds the DMLC coordinator
        # port; followers wait for it within the bootstrap window. The
        # probe ring starts first so the join record can publish its port.
        self._ring = _dist.ProbeRing()
        if self.rank == 0:
            host_s, _, port_s = self.coordinator.rpartition(":")
            try:
                self._kv_server = _dist.PodKVServer(port=int(port_s))
            except (OSError, ValueError) as exc:
                raise RuntimeError(
                    "pod bootstrap: rank 0 could not bind the "
                    "control-plane port of %s: %s"
                    % (self.coordinator, exc))
        self._kv_client = _dist.PodKVClient(self.coordinator)
        if not self._kv_client.ping(self.bootstrap_timeout):
            raise _dist.BootstrapTimeout(
                "pod bootstrap: the control plane at %s never answered "
                "within %.0fs — is rank 0's coordinator up?"
                % (self.coordinator, self.bootstrap_timeout))
        _dist.set_kv_backend(self._kv_client)
        # plain liveness beat: it freezes exactly when this PROCESS does
        # (killed, or SIGSTOPped like a stuck host) — which is the one
        # signal that justifies EVICTING a host. A wedged CHILD with a
        # live supervisor is deliberately not an eviction signal:
        # bulk-synchronous training stalls symmetrically (every peer
        # blocks in the same collective), so child-progress coupling
        # would make every host judge itself dead at once. That case is
        # the stall watchdog's (pod-wide restart, _monitor). Published
        # under the ORIGINAL pod rank: identity survives re-hosting.
        _dist.heartbeat_start(period=self.heartbeat_period,
                              as_rank=self.rank)
        _profiler.set_gauge("elastic_leader", 0)
        self._bb = _blackbox()
        if self._bb is not None:
            self._bb.set_identity(rank=self.rank, role="coord")
            self._bb.record("pod", "bootstrap", rank=self.rank,
                            world=self.world,
                            coordinator=self.coordinator)
        # opt-in /metrics endpoint for the SUPERVISOR itself (the
        # elastic_* counters + the leader's straggler gauges; training
        # children expose their own through serve/user code): stdlib
        # HTTP over the profiler registries — no jax backend is ever
        # initialized in this process
        self._metrics = self._start_metrics()
        self._workdir = tempfile.mkdtemp(prefix="mxpod_r%d_" % self.rank)
        restore_sig = self._install_forwarder()
        restore_usr1 = self._install_coordsvc_handler()
        gen = 0
        prev_world: Optional[int] = None
        try:
            while True:
                if self._terminated:
                    log.warning("pod: coordinator was SIGTERMed between "
                                "generations; not restarting")
                    return 143
                if gen > 0:
                    # let liveness settle before deciding membership: a
                    # freshly-dead host's beat counter needs one full
                    # staleness window of non-advancement before
                    # dead_ranks can call it (otherwise a rendezvous
                    # right after a crash re-admits the corpse and the
                    # next generation bootstraps against a ghost)
                    self._settle()
                if self._terminated:
                    # SIGTERM during the settle window: leave BEFORE
                    # joining the rendezvous — a join we then abandon
                    # would put a ghost in the membership and stall the
                    # survivors' data-plane bootstrap for a full timeout
                    log.warning("pod: coordinator was SIGTERMed while "
                                "settling; not joining generation %d",
                                gen)
                    return 143
                self._progress_path = os.path.join(
                    self._workdir, "progress-g%d" % gen)
                try:
                    rec = self._rendezvous(gen)
                except Exception:                          # noqa: BLE001
                    if gen == 0:
                        raise          # bootstrap errors stay legible
                    # the control plane died BEFORE or DURING this
                    # rendezvous (leader lost while we were handling a
                    # child death, or a cascade mid-rendezvous):
                    # adjudicate and fail over like the monitor would,
                    # then RETRY the SAME generation on the re-hosted
                    # control plane — peers that took the monitor path
                    # arrive at this generation number too, and the new
                    # KV incarnation starts empty, so the half-published
                    # join cannot linger
                    log.warning("pod: generation-%d rendezvous lost the "
                                "control plane; adjudicating over the "
                                "probe ring", gen)
                    if self._adjudicate(self.members) != "leader-lost" \
                            or not self._failover():
                        _dist.heartbeat_stop()
                        return 1
                    # the retry consumes restart budget like every other
                    # fail-over: a flapping elected host (each re-hosted
                    # control plane dying before it publishes the
                    # membership) must exhaust the budget and exit for a
                    # job restart, never cycle this generation forever
                    if self.restarts >= self.max_restarts:
                        log.error("pod: restart budget exhausted (%d) "
                                  "during rendezvous fail-over; giving "
                                  "up", self.max_restarts)
                        _dist.heartbeat_stop()
                        return 1
                    self.restarts += 1
                    _profiler.incr_counter("elastic_restart")
                    continue
                if rec is None:
                    log.error("pod: this host (rank %d) was judged dead "
                              "and evicted from generation %d; exiting "
                              "%d for the cluster manager",
                              self.rank, gen, SELF_DEAD_RC)
                    _dist.heartbeat_stop()
                    return SELF_DEAD_RC
                self._sync_clock()
                if self._bb is not None:
                    self._bb.record("pod", "rendezvous", gen=gen,
                                    members=list(rec["ranks"]),
                                    leader=self.leader,
                                    clock_offset_s=self.clock_offset)
                    self._bb.flush("rendezvous-g%d" % gen)
                members = rec["ranks"]
                world = len(members)
                _profiler.set_gauge("elastic_world", world)
                if prev_world is not None and world != prev_world:
                    self.reshards += 1
                    _profiler.incr_counter("elastic_reshard")
                    log.warning("pod: world size %d -> %d; children "
                                "reshard-on-load", prev_world, world)
                prev_world = world
                env = self._child_env(gen, rec)
                if self._terminated:
                    # the SIGTERM landed during settle/rendezvous (no
                    # child alive to forward to): do not spawn a fresh
                    # child just to hard-kill it
                    log.warning("pod: coordinator was SIGTERMed during "
                                "rendezvous; not starting generation %d",
                                gen)
                    return 143
                log.info("pod generation %d (rank %d/%d, world %d): %s",
                         gen, self.rank, self.world, world,
                         " ".join(self.argv))
                self._gen = gen
                self._child = subprocess.Popen(self.argv, env=env)
                outcome = self._monitor(members)
                self._child = None
                if self._bb is not None:
                    self._bb.record("pod", "generation-end", gen=gen,
                                    outcome=str(outcome))
                    self._bb.flush("g%d-%s" % (gen, outcome))
                if outcome == "done":
                    return 0
                if outcome == "terminated":
                    return 143
                if outcome == "self-dead":
                    _dist.heartbeat_stop()
                    return SELF_DEAD_RC
                if outcome == "control-plane-lost":
                    # minority side of a partition: a job restart is the
                    # only sound recovery (never SELF_DEAD_RC — nothing
                    # says this MACHINE is broken)
                    _dist.heartbeat_stop()
                    return 1
                if outcome == "leader-lost":
                    # the control plane died but a healthy majority
                    # survives: elect + re-host, then re-rendezvous at
                    # the next generation like any other host death
                    if not self._failover():
                        _dist.heartbeat_stop()
                        log.error("pod: leader fail-over could not "
                                  "complete; ending the pod for a job "
                                  "restart")
                        return 1
                # "drained" (peer death), "leader-lost" (fail-over) and
                # a child crash/preemption all consume restart budget: a
                # flapping pod must not relaunch forever
                if self.restarts >= self.max_restarts:
                    rc = outcome if isinstance(outcome, int) else 1
                    log.error("pod: restart budget exhausted (%d); "
                              "giving up with rc=%d",
                              self.max_restarts, rc)
                    return rc
                self.restarts += 1
                _profiler.incr_counter("elastic_restart")
                gen += 1
        finally:
            _dist.heartbeat_stop()
            if self._ring is not None:
                self._ring.stop()
            if self._metrics is not None:
                try:
                    self._metrics.close()
                except Exception:                          # noqa: BLE001
                    pass
            if restore_sig is not None:
                restore_sig()
            if restore_usr1 is not None:
                restore_usr1()
            # NB: a hosted KV server is deliberately NOT stopped here —
            # the done barrier in main() still rides it; the hard exit
            # reaps it

    def _install_coordsvc_handler(self):
        """SIGUSR1 = the ``coordsvc`` fault kind's delivery channel: set
        ONE flag (async-signal-safe); the monitor loop performs the
        actual service kill."""
        if not hasattr(signal, "SIGUSR1"):
            return None
        try:
            prev = signal.getsignal(signal.SIGUSR1)

            def _handler(_signum, _frame):
                self._coordsvc_kill = True

            signal.signal(signal.SIGUSR1, _handler)
        except (ValueError, OSError):
            return None             # not the main thread

        def _restore():
            try:
                signal.signal(signal.SIGUSR1, prev)
            except (ValueError, OSError, TypeError):
                pass

        return _restore

    def _settle(self) -> None:
        """One full staleness window of liveness observation before a
        rendezvous decides membership."""
        from .parallel import dist as _dist
        _dist.dead_ranks(stale_after=self.stale_after, timeout_ms=1000,
                         ranks=list(self.members))  # prime observations
        deadline = time.monotonic() + self.stale_after \
            + 2.0 * self.heartbeat_period
        while not self._terminated:
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.25, left))

    def _monitor(self, members):
        """Watch the child AND the pod. Returns ``"done"`` (child exit
        0), ``"terminated"`` (supervisor SIGTERMed), ``"self-dead"``
        (our own heartbeat went stale — wedged child), ``"drained"`` (a
        peer died/wedged or requested a pod-wide restart; child drained,
        rendezvous next generation), ``"leader-lost"`` (the control
        plane is unreachable but the probe ring confirms a healthy
        majority — fail over), ``"control-plane-lost"`` (unreachable AND
        this host is a probe-ring minority: the partitioned side exits
        for a job restart), or the child's nonzero exit code
        (crash/preemption — published as a pod-wide restart request:
        SPMD training cannot restart one rank alone, every host must
        drain and re-enter together)."""
        import json
        from . import profiler as _profiler
        from .parallel import dist as _dist
        _dist.reset_liveness()
        gen = self._gen
        restart_key = "mxpod/g%d/restart" % gen
        poll = max(0.2, min(1.0, self.stale_after / 4.0))
        child = self._child
        while True:
            rc = child.poll()
            if rc == 0:
                return "done"
            if self._terminated:
                # SIGTERM aimed at the coordinator: deliver the
                # preemption notice OURSELVES (the forwarder only signals
                # whatever child existed at signal time — this child may
                # have been spawned just after), then wait out the final
                # save, escalating after the grace. No restart.
                try:
                    child.send_signal(signal.SIGTERM)
                except OSError:
                    pass
                try:
                    child.wait(timeout=self.drain_grace)
                except subprocess.TimeoutExpired:
                    child.kill()
                    child.wait()
                return "terminated"
            if rc is not None:
                if rc == 143:
                    _profiler.incr_counter("elastic_preempt")
                    log.warning("pod: child preempted (exit 143)")
                else:
                    _profiler.incr_counter("elastic_crash")
                    log.warning("pod: child died (%s)",
                                "signal %d" % -rc if rc < 0
                                else "exit %d" % rc)
                if self._bb is not None:
                    self._bb.record("pod", "child-exit", gen=gen, rc=rc)
                try:
                    _dist.kv_set(restart_key,
                                 json.dumps({"rank": self.rank,
                                             "rc": rc}))
                except Exception:                          # noqa: BLE001
                    # a dark control plane must not mask the child's
                    # status; the next loop/generation adjudicates it
                    log.warning("pod: could not publish the pod-wide "
                                "restart request (control plane dark?)")
                return rc if rc != 0 else 1
            if self._coordsvc_kill:
                # SIGUSR1 from a child's coordsvc fault: perform the
                # abrupt service kill OUTSIDE the handler (flag-only
                # handlers; the repo's signal-unsafe lint rule)
                self._coordsvc_kill = False
                if self._bb is not None:
                    self._bb.record("pod", "coordsvc-kill", gen=gen)
                self._kill_control_plane()
            self._refresh_straggler_gauges(members)
            dead = self._dead_peers(members)
            if len(dead) >= len(members):
                # EVERY member unreadable, ourselves included: the KV
                # control plane itself is unreachable. Re-observe once
                # (a transient server hiccup must not trigger an
                # election), then adjudicate over the probe ring: a
                # healthy majority fails over in place, a minority
                # partition drains and exits for a job restart.
                time.sleep(min(1.0, self.heartbeat_period))
                dead = self._dead_peers(members)
                if len(dead) >= len(members):
                    outcome = self._adjudicate(members)
                    if self._bb is not None:
                        self._bb.record("pod", "adjudicate", gen=gen,
                                        outcome=outcome)
                        self._bb.flush("adjudicate")
                    self._drain_child()
                    return outcome
            if self.rank in dead:
                # defensive: our own beat stopped advancing (publisher
                # thread died, coordinator-side eviction) — the pod has
                # already written us off; do not fight it
                log.error("pod: our own heartbeat went stale; draining "
                          "and leaving the pod")
                self._drain_child()
                return "self-dead"
            dead = [r for r in dead if r != self.rank]
            if dead:
                self.dead_hosts += len(dead)
                _profiler.incr_counter("elastic_dead_host", len(dead))
                log.warning("pod: host rank(s) %s dead or wedged past "
                            "the %.0fs deadline; draining for "
                            "re-rendezvous at the surviving world",
                            dead, self.stale_after)
                if self._bb is not None:
                    self._bb.record("pod", "dead-hosts", gen=gen,
                                    ranks=dead)
                    self._bb.record("pod", "drain", gen=gen)
                    self._bb.flush("dead-hosts")
                self._drain_child()
                return "drained"
            try:
                restart_req = _dist.kv_get(restart_key, 50)
            except Exception:                              # noqa: BLE001
                restart_req = None      # KV flake past its retry budget
            if restart_req is not None:
                log.warning("pod: a peer requested a pod-wide restart "
                            "of generation %d; draining", gen)
                self._drain_child()
                return "drained"
            if self.stall_after > 0 and self._progress_path:
                # local stall watchdog (opt-in): our child stopped
                # advancing but every supervisor is alive — one host's
                # wedged child stalls the whole bulk-synchronous pod,
                # so the sound response is a POD-WIDE restart, never an
                # eviction (the stall is symmetric; whoever notices
                # first requests it for everyone)
                try:
                    # wall-clock on BOTH sides: st_mtime is wall-clock,
                    # so monotonic() cannot be compared against it
                    stalled = (time.time()  # mx-lint: allow(wall-clock)
                               - os.stat(self._progress_path).st_mtime
                               ) > self.stall_after
                except OSError:
                    stalled = False      # no batch yet: startup/compile
                if stalled:
                    _profiler.incr_counter("elastic_stall")
                    log.warning("pod: child progress stalled past "
                                "%.0fs; requesting a pod-wide restart",
                                self.stall_after)
                    if self._bb is not None:
                        # the watchdog-stall flush: a wedged child is a
                        # post-mortem moment even though nothing died
                        self._bb.record("pod", "stall", gen=gen,
                                        stall_after=self.stall_after)
                        self._bb.flush("stall")
                    try:
                        _dist.kv_set(restart_key, json.dumps(
                            {"rank": self.rank, "stall": True}))
                    except Exception:                      # noqa: BLE001
                        pass        # dark control plane: drain anyway
                    self._drain_child()
                    return "drained"
            time.sleep(poll)

    # the SIGTERM forwarder is identical to the Supervisor's
    _install_forwarder = Supervisor._install_forwarder


def supervise(argv: Sequence[str], **kwargs) -> int:
    """One-call form: build a :class:`Supervisor` and run it."""
    return Supervisor(argv, **kwargs).run()


def _parse_schedule(s: str) -> List[int]:
    parts = [p for p in re.split(r"[,x\s]+", s.strip()) if p]
    sched = [int(p) for p in parts]
    if not sched or any(w < 1 for w in sched):
        raise argparse.ArgumentTypeError(
            "--world-schedule wants positive device counts, e.g. 8,4,2")
    return sched


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.elastic",
        description="preemption-aware elastic training supervisor: runs "
                    "a training command in a child process, restarts it "
                    "on preemption (exit 143) or crash at the current "
                    "device-set size, bounded with backoff")
    parser.add_argument("--max-restarts", type=int, default=None)
    parser.add_argument("--backoff", type=float, default=None,
                        help="base seconds of the exponential backoff")
    parser.add_argument("--backoff-max", type=float, default=None)
    parser.add_argument("--world-schedule", type=_parse_schedule,
                        default=None,
                        help="test rig: host device count per attempt, "
                             "e.g. 8,4,2 (last entry repeats)")
    parser.add_argument("--coordinated", action="store_true",
                        help="multi-host pod mode: run ONE per-host "
                             "coordinator under tools/launch.py -n N "
                             "(control-plane heartbeats, pod-wide drain/"
                             "reshard/resume on host death — see module "
                             "docstring)")
    parser.add_argument("--drain-grace", type=float, default=None,
                        help="coordinated: seconds between the drain "
                             "SIGTERM and the SIGKILL escalation")
    parser.add_argument("--stale-after", type=float, default=None,
                        help="coordinated: heartbeat staleness deadline "
                             "(default MXNET_KVSTORE_HEARTBEAT_STALE_SECS)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="child command (prefix with -- to separate)")
    args = parser.parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no child command given")
    logging.basicConfig(level=logging.INFO,
                        format="[elastic] %(message)s")
    if args.coordinated:
        import json
        coord = PodCoordinator(command, max_restarts=args.max_restarts,
                               drain_grace=args.drain_grace,
                               stale_after=args.stale_after)
        try:
            rc = coord.run()
        except SystemExit as exc:
            rc = int(exc.code) if isinstance(exc.code, int) else 1
        except BaseException:                              # noqa: BLE001
            # an escaping error (e.g. the leader's host died and the
            # control plane with it) must still reach the HARD exit
            # below — the normal interpreter path runs jax's atexit
            # distributed-shutdown barrier, which hangs/aborts over the
            # dead pod members this mode exists to survive
            import traceback
            traceback.print_exc()
            rc = 1
        from . import profiler as _profiler
        # machine-readable exit record: the pod drill (and operators'
        # log scrapers) assert on these without reaching into the process
        print("POD-COORDINATOR-EXIT rank=%d rc=%d restarts=%d "
              "reshards=%d dead_hosts=%d failovers=%d counters=%s"
              % (coord.rank, rc, coord.restarts, coord.reshards,
                 coord.dead_hosts, coord.leader_failovers,
                 json.dumps({k: v for k, v in
                             _profiler.counters().items()
                             if k.startswith("elastic")},
                            sort_keys=True)), flush=True)
        bb = _blackbox()
        if bb is not None:
            # the coordinator's CLEAN-exit marker: the post-mortem CLI
            # reads a final "exit" flush as "this rank did not die"
            bb.record("pod", "exit", rc=rc, restarts=coord.restarts,
                      failovers=coord.leader_failovers)
            bb.flush("exit")
        sys.stdout.flush()
        sys.stderr.flush()
        # Exit order: the CURRENT leader (not necessarily rank 0 after a
        # fail-over) hosts the control-plane KV service, so it leaves
        # LAST: members publish done and the leader collects from the
        # CURRENT membership with a bounded per-rank wait (evicted and
        # dead hosts are not waited on at all). With the PodKV control
        # plane a member outliving the leader is harmless — per-request
        # sockets, no fatal client abort — the ordering just keeps the
        # done barrier meaningful for operators' log scrapers.
        try:
            from .parallel import dist as _dist
            _dist.kv_set("mxpod/done/%d" % coord.rank, str(rc))
            if coord.rank == coord.leader:
                for r in coord.members:
                    if r != coord.rank:
                        _dist.kv_get("mxpod/done/%d" % r, 5000)
        except Exception:                                  # noqa: BLE001
            pass    # a broken control plane must not mask the exit code
        # HARD exit: the training CHILDREN's jax atexit
        # distributed-shutdown barrier is their problem (they are
        # reaped); the coordinator itself never initializes jax, but the
        # hard exit keeps the exit record the LAST observable act no
        # matter what library atexit hooks accumulated.
        os._exit(rc if 0 <= rc < 256 else 1)
    return supervise(command, max_restarts=args.max_restarts,
                     backoff=args.backoff, backoff_max=args.backoff_max,
                     world_schedule=args.world_schedule)


if __name__ == "__main__":
    sys.exit(main())

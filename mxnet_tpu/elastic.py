"""Preemption-aware elastic training supervisor (ROADMAP item 4).

``fit`` already survives a preemption NOTICE: SIGTERM lands a final
synchronous checkpoint and exits 143 (PR 5). This module supplies the
missing half — the thing that *re-enters* training after the preemption:

* run the training program in a CHILD process (a supervisor that shares
  the training process dies with it — only a process boundary survives
  ``kill -9``);
* treat exit 143 (clean preemption) and any crash (signal death,
  non-zero exit) as a restartable event, bounded by
  ``MXNET_TPU_ELASTIC_MAX_RESTARTS`` with exponential backoff + jitter;
* re-probe the visible device set between attempts and re-launch the
  child at the NEW world size (on preemptible capacity the replacement
  slice is routinely smaller or larger than the one that died);
* the child resumes from the newest valid checkpoint
  (``resume_dir(base)``) — reshard-on-load re-lays every array out onto
  whatever mesh the new world size builds, so an 8-chip checkpoint
  restores onto 4 chips, 2, or 1 (``docs/architecture/elastic.md``).

The supervisor itself is deliberately framework-light: this module
touches only stdlib + the config/profiler modules, and the supervisor
process must never INITIALIZE a jax backend (``python -m`` necessarily
imports the package, which imports the jax library — but a backend pins
its device view for the life of the process, so every device query runs
in a throwaway subprocess instead: :func:`probe_world`). A regression
test runs the supervisor under an unresolvable ``JAX_PLATFORMS`` so any
in-process backend initialization fails loudly.

CLI::

    python -m mxnet_tpu.elastic [--max-restarts N] [--backoff S]
        [--world-schedule 8,4,2] -- python train.py --my-args

Multi-host pod mode (ISSUE 11)::

    tools/launch.py -n N --coordinated -- python train.py ...
    # == every host runs: python -m mxnet_tpu.elastic --coordinated -- ...

Each host runs ONE :class:`PodCoordinator` (rank/world from the same
DMLC_* env the launcher sets). The coordinators form the pod's control
plane over the ``jax.distributed`` coordination service — a
coordination CLIENT only; the no-jax-backend discipline above still
holds — and publish liveness heartbeats (``dist.heartbeat_start``): a
host that dies (SIGKILL) or freezes whole (SIGSTOP — a stuck machine)
stops beating and is caught by the
``MXNET_KVSTORE_HEARTBEAT_STALE_SECS`` deadline. On a death the
survivors DRAIN (SIGTERM the child, escalate to SIGKILL after
``MXNET_TPU_ELASTIC_DRAIN_GRACE``), re-rendezvous at the surviving
world size (generation bump; the leader — the lowest live rank —
publishes membership + a fresh data-plane coordinator port), and
relaunch: the children resume from the newest COMPLETE checkpoint,
resharding onto the new world. A training CHILD failing with its
supervisor alive (crash, preemption, or — with the opt-in
``MXNET_TPU_ELASTIC_STALL_SECS`` watchdog — a wedged child) triggers a
POD-WIDE restart at the unchanged membership instead: bulk-synchronous
SPMD cannot restart one rank alone, and a child-level stall is
symmetric across the pod (every peer blocks in the same collective),
so eviction would be wrong. Counters: ``elastic_dead_host``,
``elastic_reshard``, ``elastic_restart``, ``elastic_stall``; gauge
``elastic_world``. Rank 0 hosts the control plane (like the
reference's ps-lite scheduler): rank 0's host dying ends the pod — the
cluster manager restarts the whole job, which then resumes from
checkpoints.

Environment exported to every attempt:

* ``MXNET_TPU_ELASTIC_ATTEMPT`` — 0-based attempt index (the training
  script can key per-attempt behavior on it; the fault drills do);
* ``MXNET_TPU_ELASTIC_RESUMED=1`` — on every attempt after the first;
* with a world schedule (the virtual-mesh test rig), ``XLA_FLAGS`` is
  rewritten with ``--xla_force_host_platform_device_count=<n>`` so the
  child binds at the scheduled world size.

Counters: ``elastic_restart`` (every re-entry), ``elastic_preempt``
(exit-143 children), ``elastic_crash`` (signal/non-zero children),
``elastic_reshard`` (re-entries whose world size changed) and the
``elastic_world`` gauge.
"""
from __future__ import annotations

import argparse
import logging
import os
import random as _pyrandom
import re
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

__all__ = ["Supervisor", "PodCoordinator", "supervise", "resume_dir",
           "probe_world", "main"]

log = logging.getLogger(__name__)

_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count"


def resume_dir(base: str) -> Optional[str]:
    """``base`` if it holds at least one VALID checkpoint, else None —
    the one-liner a training script needs to pass
    ``fit(resume_from=...)`` only when there is something to resume
    (attempt 0 of an elastic run starts from scratch)."""
    from .checkpoint import format as _format
    for _step, path in reversed(_format.list_checkpoints(str(base))):
        if _format.probe_valid(path):
            return str(base)
    return None


def probe_world(env: Optional[dict] = None,
                timeout: float = 120.0) -> Optional[int]:
    """Re-probe the visible device set in a THROWAWAY subprocess (jax
    caches its backend for the life of a process — the supervisor must
    never bind one). Returns the device count, or None when the probe
    fails (backend wedged mid-preemption: the caller backs off and
    retries on the next attempt)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
            env=env if env is not None else os.environ.copy())
        if out.returncode == 0:
            return int(out.stdout.strip().splitlines()[-1])
    except (subprocess.SubprocessError, ValueError, IndexError, OSError):
        pass
    return None


def _with_device_count(flags: str, n: int) -> str:
    """XLA_FLAGS with the host-platform device count pinned to ``n``."""
    kept = [f for f in flags.split()
            if not f.startswith(_DEVCOUNT_FLAG + "=")]
    kept.append("%s=%d" % (_DEVCOUNT_FLAG, n))
    return " ".join(kept)


class Supervisor(object):
    """Run one training command elastically; see module docstring.

    Parameters
    ----------
    argv : list of str
        The child command. A leading ``*.py`` token is run with the
        current interpreter.
    max_restarts, backoff, backoff_max : optional
        Defaults from the ``MXNET_TPU_ELASTIC_*`` knobs.
    world_schedule : list of int, optional
        Virtual-mesh test rig: attempt ``i`` runs at
        ``schedule[min(i, len-1)]`` host devices (via ``XLA_FLAGS``).
        Without a schedule the device set is re-probed from the real
        backend between attempts (``probe_world``).
    jitter_seed : optional
        Seeds the backoff jitter for deterministic tests.
    """

    def __init__(self, argv: Sequence[str],
                 max_restarts: Optional[int] = None,
                 backoff: Optional[float] = None,
                 backoff_max: Optional[float] = None,
                 world_schedule: Optional[Sequence[int]] = None,
                 env: Optional[dict] = None,
                 jitter_seed: Optional[int] = None,
                 on_attempt: Optional[Callable[[int, dict], None]] = None):
        from . import config as _config
        argv = list(argv)
        if argv and argv[0].endswith(".py"):
            argv.insert(0, sys.executable)
        if not argv:
            raise ValueError("elastic supervisor needs a child command")
        self.argv = argv
        self.max_restarts = int(
            _config.get("MXNET_TPU_ELASTIC_MAX_RESTARTS")
            if max_restarts is None else max_restarts)
        self.backoff = float(_config.get("MXNET_TPU_ELASTIC_BACKOFF")
                             if backoff is None else backoff)
        self.backoff_max = float(
            _config.get("MXNET_TPU_ELASTIC_BACKOFF_MAX")
            if backoff_max is None else backoff_max)
        self.world_schedule = [int(w) for w in world_schedule] \
            if world_schedule else None
        self.env = dict(env) if env is not None else None
        self._rng = _pyrandom.Random(jitter_seed)
        self._on_attempt = on_attempt
        self.restarts = 0
        self.reshards = 0
        self._child: Optional[subprocess.Popen] = None
        self._terminated = False

    # ------------------------------------------------------------ signals
    def _install_forwarder(self):
        """Forward a SIGTERM aimed at the supervisor to the child (the
        scheduler preempts the whole allocation — the child must get its
        preemption notice) and stop restarting. Flag-set + os.kill only:
        anything allocation- or lock-heavy is unsafe in a handler."""
        if not hasattr(signal, "SIGTERM"):
            return None
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _handler(_signum, _frame):
                self._terminated = True
                child = self._child
                if child is not None:
                    try:
                        os.kill(child.pid, signal.SIGTERM)
                    except OSError:
                        pass        # already gone



            signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError):
            return None         # not the main thread

        def _restore():
            try:
                signal.signal(signal.SIGTERM, prev)
            except (ValueError, OSError, TypeError):
                pass

        return _restore

    def _backoff_sleep(self, delay: float) -> None:
        """Backoff that a between-attempts SIGTERM can cut short: PEP 475
        resumes one long ``time.sleep`` after the flag-only handler
        returns, so sleep in small slices and re-check the flag (an
        Event would be cleaner but ``Event.set`` takes a lock — the
        signal-unsafe hazard class the repo lint rejects)."""
        deadline = time.monotonic() + max(0.0, delay)
        while not self._terminated:
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.25, left))

    # ------------------------------------------------------------- world
    def _world_for_attempt(self, attempt: int) -> Optional[int]:
        if self.world_schedule:
            i = min(attempt, len(self.world_schedule) - 1)
            return self.world_schedule[i]
        return None

    def _env_for_attempt(self, attempt: int, world: Optional[int]) -> dict:
        env = dict(self.env if self.env is not None else os.environ)
        env["MXNET_TPU_ELASTIC_ATTEMPT"] = str(attempt)
        if attempt > 0:
            env["MXNET_TPU_ELASTIC_RESUMED"] = "1"
        if world is not None:
            env["XLA_FLAGS"] = _with_device_count(
                env.get("XLA_FLAGS", ""), world)
        return env

    # -------------------------------------------------------------- run
    def run(self) -> int:
        from . import profiler as _profiler
        restore_sig = self._install_forwarder()
        attempt = 0
        prev_world: Optional[int] = None
        try:
            while True:
                if self._terminated:
                    # the preemption landed BETWEEN attempts (backoff
                    # sleep / world probe): do not spawn a fresh child
                    # just to have the platform hard-kill it
                    log.warning("elastic: supervisor was SIGTERMed "
                                "between attempts; not restarting")
                    return 143
                world = self._world_for_attempt(attempt)
                env = self._env_for_attempt(attempt, world)
                if world is None:
                    # real backend: ask a throwaway process what is
                    # actually visible right now (logging + reshard
                    # accounting; the child binds whatever it sees)
                    world = probe_world(env)
                if world is not None:
                    _profiler.set_gauge("elastic_world", world)
                if attempt > 0 and world is not None \
                        and prev_world is not None and world != prev_world:
                    self.reshards += 1
                    _profiler.incr_counter("elastic_reshard")
                    log.warning("elastic: world size %d -> %d; the child "
                                "will reshard-on-load", prev_world, world)
                prev_world = world if world is not None else prev_world
                if self._on_attempt is not None:
                    self._on_attempt(attempt, env)
                log.info("elastic attempt %d (world=%s): %s",
                         attempt, world, " ".join(self.argv))
                self._child = subprocess.Popen(self.argv, env=env)
                rc = self._child.wait()
                self._child = None
                if rc == 0:
                    return 0
                if self._terminated:
                    # the preemption was aimed at US — do not restart,
                    # propagate the conventional status
                    log.warning("elastic: supervisor was SIGTERMed; "
                                "child exited %d; not restarting", rc)
                    return 143
                if rc == 143:
                    _profiler.incr_counter("elastic_preempt")
                    log.warning("elastic: child preempted (exit 143)")
                else:
                    _profiler.incr_counter("elastic_crash")
                    log.warning("elastic: child died (%s)",
                                "signal %d" % -rc if rc < 0
                                else "exit %d" % rc)
                if self.restarts >= self.max_restarts:
                    log.error("elastic: restart budget exhausted "
                              "(%d); giving up with rc=%d",
                              self.max_restarts, rc)
                    return rc if rc != 0 else 1
                self.restarts += 1
                _profiler.incr_counter("elastic_restart")
                delay = min(self.backoff_max,
                            self.backoff * (2 ** (self.restarts - 1)))
                delay *= 1.0 + 0.25 * self._rng.random()
                log.info("elastic: restart %d/%d in %.2fs",
                         self.restarts, self.max_restarts, delay)
                self._backoff_sleep(delay)
                attempt += 1
        finally:
            if restore_sig is not None:
                restore_sig()


# exit status of a coordinator that judged its OWN host dead (wedged
# child): the host cannot trust itself, so it leaves the pod and lets
# the cluster manager replace the machine (EX_TEMPFAIL)
SELF_DEAD_RC = 75


class PodCoordinator(object):
    """Per-host pod supervisor (``--coordinated``; module docstring).

    One coordinator runs on every host. Control plane: the
    ``jax.distributed`` coordination service on the DMLC coordinator
    address (a TCP client — no jax backend is ever initialized in this
    process). Liveness: plain heartbeats that freeze exactly when this
    process does. A dead or frozen host triggers pod-wide drain →
    rendezvous at the surviving world → relaunch, with the children
    resuming from the newest complete checkpoint (reshard-on-load); a
    child-level failure triggers a pod-wide restart at the unchanged
    membership.
    """

    def __init__(self, argv: Sequence[str],
                 max_restarts: Optional[int] = None,
                 heartbeat_period: Optional[float] = None,
                 stale_after: Optional[float] = None,
                 stall_after: Optional[float] = None,
                 drain_grace: Optional[float] = None,
                 rendezvous_window: Optional[float] = None,
                 env: Optional[dict] = None,
                 advertise_host: Optional[str] = None):
        from . import config as _config
        from .parallel import dist as _dist
        argv = list(argv)
        if argv and argv[0].endswith(".py"):
            argv.insert(0, sys.executable)
        if not argv:
            raise ValueError("pod coordinator needs a child command")
        self.argv = argv
        cluster = _dist.cluster_env()
        if cluster is None:
            raise RuntimeError(
                "--coordinated needs the launcher env: run every host "
                "through tools/launch.py -n N --coordinated (sets "
                "DMLC_PS_ROOT_URI/PORT, DMLC_NUM_WORKER, DMLC_WORKER_ID)")
        self.rank = cluster["rank"]
        self.world = cluster["num_workers"]
        self.coordinator = cluster["coordinator"]
        self.max_restarts = int(
            _config.get("MXNET_TPU_ELASTIC_MAX_RESTARTS")
            if max_restarts is None else max_restarts)
        self.heartbeat_period = float(
            _config.get("MXNET_TPU_HEARTBEAT_PERIOD")
            if heartbeat_period is None else heartbeat_period)
        self.stale_after = float(
            _config.get("MXNET_KVSTORE_HEARTBEAT_STALE_SECS")
            if stale_after is None else stale_after)
        self.stall_after = float(
            _config.get("MXNET_TPU_ELASTIC_STALL_SECS")
            if stall_after is None else stall_after)
        self.drain_grace = float(
            _config.get("MXNET_TPU_ELASTIC_DRAIN_GRACE")
            if drain_grace is None else drain_grace)
        self.rendezvous_window = float(
            max(2.0 * self.stale_after, 10.0)
            if rendezvous_window is None else rendezvous_window)
        self.bootstrap_timeout = float(_config.get("MXNET_TPU_DIST_TIMEOUT"))
        self.env = dict(env) if env is not None else None
        if advertise_host is None:
            advertise_host = os.environ.get("MXNET_TPU_POD_HOST")
        if advertise_host is None:
            if self.rank == 0:
                advertise_host = self.coordinator.rsplit(":", 1)[0]
            else:
                import socket
                advertise_host = socket.gethostname()
        self.advertise = advertise_host
        self.restarts = 0
        self.reshards = 0
        self.dead_hosts = 0
        self._child: Optional[subprocess.Popen] = None
        self._terminated = False
        self._progress_path: Optional[str] = None
        self._workdir: Optional[str] = None
        self._gen = 0

    # ------------------------------------------------------------ liveness
    def _dead_peers(self, members) -> List[int]:
        from .parallel import dist as _dist
        dead = _dist.dead_ranks(stale_after=self.stale_after,
                                timeout_ms=1000)
        return [r for r in dead if r in members]

    # ---------------------------------------------------------- rendezvous
    def _rendezvous(self, gen: int) -> Optional[dict]:
        """Agree on generation ``gen``'s membership. Every live
        coordinator publishes a join key; the leader (lowest live rank)
        collects joins within the rendezvous window and publishes the
        member list + a fresh data-plane coordinator port; followers
        wait for that record (bounded). Returns the record, or None when
        this rank was judged dead and evicted."""
        import json
        from .parallel import dist as _dist
        _dist.kv_set("mxpod/g%d/join/%d" % (gen, self.rank),
                     json.dumps({"host": self.advertise}))
        dead = set()
        if gen > 0:
            dead = set(_dist.dead_ranks(stale_after=self.stale_after,
                                        timeout_ms=1000))
            dead.discard(self.rank)   # we are here, deciding to continue
        leader = min(r for r in range(self.world) if r not in dead)
        key = "mxpod/g%d/members" % gen
        if leader == self.rank:
            members = []
            deadline = time.monotonic() + (
                self.bootstrap_timeout if gen == 0
                else self.rendezvous_window)
            for r in range(self.world):
                if r in dead:
                    continue
                left_ms = max(1, int((deadline - time.monotonic()) * 1000))
                raw = _dist.kv_get("mxpod/g%d/join/%d" % (gen, r), left_ms)
                if raw is not None:
                    members.append(r)
                elif gen == 0:
                    raise RuntimeError(
                        "pod rendezvous: rank %d of %d never joined "
                        "generation 0 within %.0fs — check that every "
                        "host launched its coordinator"
                        % (r, self.world, self.bootstrap_timeout))
                else:
                    log.warning("pod: rank %d missed the generation-%d "
                                "rendezvous window; continuing without "
                                "it", r, gen)
            rec = {"gen": gen, "ranks": members, "leader": self.rank,
                   "coordinator": "%s:%d" % (self.advertise,
                                             _dist.free_port())}
            _dist.kv_set(key, json.dumps(rec))
        else:
            # a follower must outwait the leader's WORST case: the full
            # collection window plus the bootstrap allowance (a follower
            # timing out on the same clock as a still-collecting leader
            # would drop a healthy host out of a recoverable pod)
            wait = self.bootstrap_timeout + self.rendezvous_window
            raw = _dist.kv_get(key, int(wait * 1000))
            if raw is None:
                raise RuntimeError(
                    "pod rendezvous: the leader never published "
                    "generation-%d membership within %.0fs (leader host "
                    "dead? rank 0's host carries the control plane)"
                    % (gen, wait))
            rec = json.loads(raw)
        if self.rank not in rec["ranks"]:
            return None                           # judged dead: evicted
        return rec

    # --------------------------------------------------------------- child
    def _child_env(self, gen: int, rec: dict) -> dict:
        env = dict(self.env if self.env is not None else os.environ)
        members = rec["ranks"]
        uri, _, port = rec["coordinator"].rpartition(":")
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": uri,
            "DMLC_PS_ROOT_PORT": port,
            "DMLC_NUM_WORKER": str(len(members)),
            "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(members.index(self.rank)),
            "MXNET_TPU_POD_GEN": str(gen),
            "MXNET_TPU_ELASTIC_COORDINATED": "1",
            "MXNET_TPU_ELASTIC_ATTEMPT": str(gen),
            "MXNET_TPU_ELASTIC_PROGRESS_FILE": self._progress_path,
        })
        if gen > 0:
            env["MXNET_TPU_ELASTIC_RESUMED"] = "1"
        return env

    def _drain_child(self) -> None:
        """Pod drain: preemption-notice SIGTERM first (the child lands a
        best-effort final save and exits 143), SIGKILL after the grace —
        a child wedged inside a collective whose peer died cannot
        observe the notice."""
        child = self._child
        if child is None or child.poll() is not None:
            return
        try:
            child.send_signal(signal.SIGTERM)
        except OSError:
            return
        try:
            child.wait(timeout=self.drain_grace)
        except subprocess.TimeoutExpired:
            log.warning("pod drain: child ignored SIGTERM for %.0fs "
                        "(wedged collective?); escalating to SIGKILL",
                        self.drain_grace)
            try:
                child.kill()
            except OSError:
                pass
            child.wait()

    # ----------------------------------------------------------------- run
    def run(self) -> int:
        import tempfile
        from . import profiler as _profiler
        from .parallel import dist as _dist
        _dist.initialize(coordinator_address=self.coordinator,
                         num_processes=self.world, process_id=self.rank)
        # plain liveness beat: it freezes exactly when this PROCESS does
        # (killed, or SIGSTOPped like a stuck host) — which is the one
        # signal that justifies EVICTING a host. A wedged CHILD with a
        # live supervisor is deliberately not an eviction signal:
        # bulk-synchronous training stalls symmetrically (every peer
        # blocks in the same collective), so child-progress coupling
        # would make every host judge itself dead at once. That case is
        # the stall watchdog's (pod-wide restart, _monitor).
        _dist.heartbeat_start(period=self.heartbeat_period)
        self._workdir = tempfile.mkdtemp(prefix="mxpod_r%d_" % self.rank)
        restore_sig = self._install_forwarder()
        gen = 0
        prev_world: Optional[int] = None
        try:
            while True:
                if self._terminated:
                    log.warning("pod: coordinator was SIGTERMed between "
                                "generations; not restarting")
                    return 143
                if gen > 0:
                    # let liveness settle before deciding membership: a
                    # freshly-dead host's beat counter needs one full
                    # staleness window of non-advancement before
                    # dead_ranks can call it (otherwise a rendezvous
                    # right after a crash re-admits the corpse and the
                    # next generation bootstraps against a ghost)
                    self._settle()
                if self._terminated:
                    # SIGTERM during the settle window: leave BEFORE
                    # joining the rendezvous — a join we then abandon
                    # would put a ghost in the membership and stall the
                    # survivors' data-plane bootstrap for a full timeout
                    log.warning("pod: coordinator was SIGTERMed while "
                                "settling; not joining generation %d",
                                gen)
                    return 143
                self._progress_path = os.path.join(
                    self._workdir, "progress-g%d" % gen)
                rec = self._rendezvous(gen)
                if rec is None:
                    log.error("pod: this host (rank %d) was judged dead "
                              "and evicted from generation %d; exiting "
                              "%d for the cluster manager",
                              self.rank, gen, SELF_DEAD_RC)
                    _dist.heartbeat_stop()
                    return SELF_DEAD_RC
                members = rec["ranks"]
                world = len(members)
                _profiler.set_gauge("elastic_world", world)
                if prev_world is not None and world != prev_world:
                    self.reshards += 1
                    _profiler.incr_counter("elastic_reshard")
                    log.warning("pod: world size %d -> %d; children "
                                "reshard-on-load", prev_world, world)
                prev_world = world
                env = self._child_env(gen, rec)
                if self._terminated:
                    # the SIGTERM landed during settle/rendezvous (no
                    # child alive to forward to): do not spawn a fresh
                    # child just to hard-kill it
                    log.warning("pod: coordinator was SIGTERMed during "
                                "rendezvous; not starting generation %d",
                                gen)
                    return 143
                log.info("pod generation %d (rank %d/%d, world %d): %s",
                         gen, self.rank, self.world, world,
                         " ".join(self.argv))
                self._gen = gen
                self._child = subprocess.Popen(self.argv, env=env)
                outcome = self._monitor(members)
                self._child = None
                if outcome == "done":
                    return 0
                if outcome == "terminated":
                    return 143
                if outcome == "self-dead":
                    _dist.heartbeat_stop()
                    return SELF_DEAD_RC
                if outcome == "control-plane-lost":
                    _dist.heartbeat_stop()
                    return 1
                # "drained" (peer death) and a child crash/preemption
                # both consume restart budget: a flapping pod must not
                # relaunch forever
                if self.restarts >= self.max_restarts:
                    rc = outcome if isinstance(outcome, int) else 1
                    log.error("pod: restart budget exhausted (%d); "
                              "giving up with rc=%d",
                              self.max_restarts, rc)
                    return rc
                self.restarts += 1
                _profiler.incr_counter("elastic_restart")
                gen += 1
        finally:
            _dist.heartbeat_stop()
            if restore_sig is not None:
                restore_sig()

    def _settle(self) -> None:
        """One full staleness window of liveness observation before a
        rendezvous decides membership."""
        from .parallel import dist as _dist
        _dist.dead_ranks(stale_after=self.stale_after,
                         timeout_ms=1000)          # prime observations
        deadline = time.monotonic() + self.stale_after \
            + 2.0 * self.heartbeat_period
        while not self._terminated:
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.25, left))

    def _monitor(self, members):
        """Watch the child AND the pod. Returns ``"done"`` (child exit
        0), ``"terminated"`` (supervisor SIGTERMed), ``"self-dead"``
        (our own heartbeat went stale — wedged child), ``"drained"`` (a
        peer died/wedged or requested a pod-wide restart; child drained,
        rendezvous next generation), or the child's nonzero exit code
        (crash/preemption — published as a pod-wide restart request:
        SPMD training cannot restart one rank alone, every host must
        drain and re-enter together)."""
        import json
        from . import profiler as _profiler
        from .parallel import dist as _dist
        _dist.reset_liveness()
        gen = self._gen
        restart_key = "mxpod/g%d/restart" % gen
        poll = max(0.2, min(1.0, self.stale_after / 4.0))
        child = self._child
        while True:
            rc = child.poll()
            if rc == 0:
                return "done"
            if self._terminated:
                # SIGTERM aimed at the coordinator: deliver the
                # preemption notice OURSELVES (the forwarder only signals
                # whatever child existed at signal time — this child may
                # have been spawned just after), then wait out the final
                # save, escalating after the grace. No restart.
                try:
                    child.send_signal(signal.SIGTERM)
                except OSError:
                    pass
                try:
                    child.wait(timeout=self.drain_grace)
                except subprocess.TimeoutExpired:
                    child.kill()
                    child.wait()
                return "terminated"
            if rc is not None:
                if rc == 143:
                    _profiler.incr_counter("elastic_preempt")
                    log.warning("pod: child preempted (exit 143)")
                else:
                    _profiler.incr_counter("elastic_crash")
                    log.warning("pod: child died (%s)",
                                "signal %d" % -rc if rc < 0
                                else "exit %d" % rc)
                _dist.kv_set(restart_key,
                             json.dumps({"rank": self.rank, "rc": rc}))
                return rc if rc != 0 else 1
            dead = self._dead_peers(members)
            if len(dead) >= len(members):
                # EVERY rank unreadable, ourselves included, means the
                # coordination service itself is gone — rank 0's host
                # died (the documented control-plane limit). That is a
                # JOB failure for the cluster manager to restart, not
                # evidence that this machine is broken: do NOT exit
                # SELF_DEAD_RC, which asks for the machine's replacement
                log.error("pod: the control plane is unreachable (rank "
                          "0's host dead?); draining and ending the pod")
                self._drain_child()
                return "control-plane-lost"
            if self.rank in dead:
                # defensive: our own beat stopped advancing (publisher
                # thread died, coordinator-side eviction) — the pod has
                # already written us off; do not fight it
                log.error("pod: our own heartbeat went stale; draining "
                          "and leaving the pod")
                self._drain_child()
                return "self-dead"
            dead = [r for r in dead if r != self.rank]
            if dead:
                self.dead_hosts += len(dead)
                _profiler.incr_counter("elastic_dead_host", len(dead))
                log.warning("pod: host rank(s) %s dead or wedged past "
                            "the %.0fs deadline; draining for "
                            "re-rendezvous at the surviving world",
                            dead, self.stale_after)
                self._drain_child()
                return "drained"
            if _dist.kv_get(restart_key, 50) is not None:
                log.warning("pod: a peer requested a pod-wide restart "
                            "of generation %d; draining", gen)
                self._drain_child()
                return "drained"
            if self.stall_after > 0 and self._progress_path:
                # local stall watchdog (opt-in): our child stopped
                # advancing but every supervisor is alive — one host's
                # wedged child stalls the whole bulk-synchronous pod,
                # so the sound response is a POD-WIDE restart, never an
                # eviction (the stall is symmetric; whoever notices
                # first requests it for everyone)
                try:
                    # wall-clock on BOTH sides: st_mtime is wall-clock,
                    # so monotonic() cannot be compared against it
                    stalled = (time.time()  # mx-lint: allow(wall-clock)
                               - os.stat(self._progress_path).st_mtime
                               ) > self.stall_after
                except OSError:
                    stalled = False      # no batch yet: startup/compile
                if stalled:
                    _profiler.incr_counter("elastic_stall")
                    log.warning("pod: child progress stalled past "
                                "%.0fs; requesting a pod-wide restart",
                                self.stall_after)
                    _dist.kv_set(restart_key, json.dumps(
                        {"rank": self.rank, "stall": True}))
                    self._drain_child()
                    return "drained"
            time.sleep(poll)

    # the SIGTERM forwarder is identical to the Supervisor's
    _install_forwarder = Supervisor._install_forwarder


def supervise(argv: Sequence[str], **kwargs) -> int:
    """One-call form: build a :class:`Supervisor` and run it."""
    return Supervisor(argv, **kwargs).run()


def _parse_schedule(s: str) -> List[int]:
    parts = [p for p in re.split(r"[,x\s]+", s.strip()) if p]
    sched = [int(p) for p in parts]
    if not sched or any(w < 1 for w in sched):
        raise argparse.ArgumentTypeError(
            "--world-schedule wants positive device counts, e.g. 8,4,2")
    return sched


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.elastic",
        description="preemption-aware elastic training supervisor: runs "
                    "a training command in a child process, restarts it "
                    "on preemption (exit 143) or crash at the current "
                    "device-set size, bounded with backoff")
    parser.add_argument("--max-restarts", type=int, default=None)
    parser.add_argument("--backoff", type=float, default=None,
                        help="base seconds of the exponential backoff")
    parser.add_argument("--backoff-max", type=float, default=None)
    parser.add_argument("--world-schedule", type=_parse_schedule,
                        default=None,
                        help="test rig: host device count per attempt, "
                             "e.g. 8,4,2 (last entry repeats)")
    parser.add_argument("--coordinated", action="store_true",
                        help="multi-host pod mode: run ONE per-host "
                             "coordinator under tools/launch.py -n N "
                             "(control-plane heartbeats, pod-wide drain/"
                             "reshard/resume on host death — see module "
                             "docstring)")
    parser.add_argument("--drain-grace", type=float, default=None,
                        help="coordinated: seconds between the drain "
                             "SIGTERM and the SIGKILL escalation")
    parser.add_argument("--stale-after", type=float, default=None,
                        help="coordinated: heartbeat staleness deadline "
                             "(default MXNET_KVSTORE_HEARTBEAT_STALE_SECS)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="child command (prefix with -- to separate)")
    args = parser.parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no child command given")
    logging.basicConfig(level=logging.INFO,
                        format="[elastic] %(message)s")
    if args.coordinated:
        import json
        coord = PodCoordinator(command, max_restarts=args.max_restarts,
                               drain_grace=args.drain_grace,
                               stale_after=args.stale_after)
        try:
            rc = coord.run()
        except SystemExit as exc:
            rc = int(exc.code) if isinstance(exc.code, int) else 1
        except BaseException:                              # noqa: BLE001
            # an escaping error (e.g. the leader's host died and the
            # control plane with it) must still reach the HARD exit
            # below — the normal interpreter path runs jax's atexit
            # distributed-shutdown barrier, which hangs/aborts over the
            # dead pod members this mode exists to survive
            import traceback
            traceback.print_exc()
            rc = 1
        from . import profiler as _profiler
        # machine-readable exit record: the pod drill (and operators'
        # log scrapers) assert on these without reaching into the process
        print("POD-COORDINATOR-EXIT rank=%d rc=%d restarts=%d "
              "reshards=%d dead_hosts=%d counters=%s"
              % (coord.rank, rc, coord.restarts, coord.reshards,
                 coord.dead_hosts,
                 json.dumps({k: v for k, v in
                             _profiler.counters().items()
                             if k.startswith("elastic")},
                            sort_keys=True)), flush=True)
        sys.stdout.flush()
        sys.stderr.flush()
        # Exit order: rank 0 hosts the coordination service, so it must
        # leave LAST — a peer whose client outlives the leader aborts
        # fatally over the closed socket. Non-leaders publish done as
        # their LAST act before the hard exit (nothing in between that
        # an abort could interrupt); rank 0 collects with a bounded
        # per-rank wait (dead hosts never publish; skip them after 5s).
        try:
            from .parallel import dist as _dist
            _dist.kv_set("mxpod/done/%d" % coord.rank, str(rc))
            if coord.rank == 0:
                for r in range(1, coord.world):
                    _dist.kv_get("mxpod/done/%d" % r, 5000)
        except Exception:                                  # noqa: BLE001
            pass    # a broken control plane must not mask the exit code
        # HARD exit: jax's atexit distributed-shutdown barrier would wait
        # on (and then abort over) pod members that died — the exact
        # event this mode exists to survive. Nothing is left to clean up:
        # the child is reaped and the exit record is flushed.
        os._exit(rc if 0 <= rc < 256 else 1)
    return supervise(command, max_restarts=args.max_restarts,
                     backoff=args.backoff, backoff_max=args.backoff_max,
                     world_schedule=args.world_schedule)


if __name__ == "__main__":
    sys.exit(main())

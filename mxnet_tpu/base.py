"""Base utilities for mxnet_tpu.

This module plays the role of the reference's ``python/mxnet/base.py`` (handle
types, dtype tables, error plumbing — reference: python/mxnet/base.py:1-347),
minus the ctypes bridge: there is no C ABI between the Python frontend and the
execution engine here — JAX/XLA *is* the native core, and the Python layer
talks to it directly.
"""
from __future__ import annotations

import numpy as _np

__all__ = [
    "MXNetError",
    "string_types",
    "numeric_types",
    "_DTYPE_NP_TO_MX",
    "_DTYPE_MX_TO_NP",
    "mx_real_t",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: python/mxnet/base.py:66)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)

# dtype enum kept for serialization compatibility with the reference's NDArray
# binary format (reference: python/mxnet/ndarray.py:54-76). Entry 7 (bfloat16)
# is a TPU-native addition with no counterpart in the 2017 reference.
_DTYPE_NP_TO_MX = {
    None: -1,
    _np.dtype(_np.float32): 0,
    _np.dtype(_np.float64): 1,
    _np.dtype(_np.float16): 2,
    _np.dtype(_np.uint8): 3,
    _np.dtype(_np.int32): 4,
    _np.dtype(_np.int8): 5,
    _np.dtype(_np.int64): 6,
}
try:  # bfloat16 is first-class on TPU
    import ml_dtypes as _ml_dtypes

    _DTYPE_NP_TO_MX[_np.dtype(_ml_dtypes.bfloat16)] = 7
except ImportError:  # pragma: no cover
    pass

_DTYPE_MX_TO_NP = {v: k for k, v in _DTYPE_NP_TO_MX.items()}

mx_real_t = _np.float32


def check_call(ret):  # pragma: no cover - API-compat shim
    """No-op shim: there is no C return code to check in the TPU build."""
    return ret

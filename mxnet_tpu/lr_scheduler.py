"""Learning-rate schedulers.

Reference: ``python/mxnet/lr_scheduler.py`` (FactorScheduler:53,
MultiFactorScheduler:94).
"""
from __future__ import annotations

import logging

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler"]


class LRScheduler(object):
    """Base: maps num_update -> lr (reference: lr_scheduler.py LRScheduler)."""

    def __init__(self, base_lr: float = 0.01):
        self.base_lr = base_lr

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every `step` updates (reference: lr_scheduler.py:53)."""

    def __init__(self, step: int, factor: float = 1.0, stop_factor_lr: float = 1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1 to make lr reduce")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update: int) -> float:
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info("Update[%d]: now learning rate arrived at %0.5e, "
                             "will not change in the future", num_update,
                             self.base_lr)
            else:
                logging.info("Update[%d]: Change learning rate to %0.5e",
                             num_update, self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """lr *= factor at each listed step (reference: lr_scheduler.py:94)."""

    def __init__(self, step, factor: float = 1.0):
        super().__init__()
        if len(step) < 1:
            raise ValueError("Schedule step must have at least one entry")
        for i, _step in enumerate(step):
            if i != 0 and step[i] <= step[i - 1]:
                raise ValueError("Schedule step must be an increasing list")
            if _step < 1:
                raise ValueError("Schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1 to make lr reduce")
        self.step = list(step)
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update: int) -> float:
        while self.cur_step_ind <= len(self.step) - 1:
            if num_update > self.step[self.cur_step_ind]:
                self.count = self.step[self.cur_step_ind]
                self.cur_step_ind += 1
                self.base_lr *= self.factor
                logging.info("Update[%d]: Change learning rate to %0.5e",
                             num_update, self.base_lr)
            else:
                return self.base_lr
        return self.base_lr


class PolyScheduler(LRScheduler):
    """Polynomial decay to zero over max_update steps (capability extension
    used by imagenet-style training scripts)."""

    def __init__(self, max_update: int, power: float = 2.0):
        super().__init__()
        self.max_update = max_update
        self.power = power

    def __call__(self, num_update: int) -> float:
        frac = min(float(num_update) / self.max_update, 1.0)
        return self.base_lr * ((1.0 - frac) ** self.power)

"""Applied rematerialization: turn the analyzer's ``remat-opportunity``
suggestion (or an explicitly named policy) into the ``jax.checkpoint``
wrapper the fused train step actually runs under.

PR 8's efficiency auditor can *name* the right ``jax.checkpoint`` policy
for a graph (``Report.extras["remat"]``) but nothing acted on it; this
module closes that loop behind one knob:

``MXNET_TPU_REMAT = off | auto | <policy-name>``

* ``off`` (default) — save all activations; this module is never
  imported on the hot path.
* ``auto`` — run the analysis graph passes over the bound symbol and
  apply exactly the policy the ``remat-opportunity`` pass suggests for
  THIS graph (``extras["remat"]["suggestion"]["policy"]``). No
  suggestion (nothing worth rematerializing) means no wrapping.
* anything else — a ``jax.checkpoint_policies`` attribute name applied
  as-is (``nothing_saveable``, ``dots_with_no_batch_dims_saveable``,
  ``dots_saveable``, ...). Unknown names raise at bind, naming the
  valid choices, instead of silently training without remat.

The legacy bool ``MXNET_EXEC_ENABLE_REMAT=1`` is kept as an alias for
``dots_with_no_batch_dims_saveable`` (its documented historical
behavior) and loses to an explicit ``MXNET_TPU_REMAT``.

Application point (``Module._build_fused_step``): with a scan plan
bound, each ``lax.scan`` body iteration — one repeated block — is
wrapped, which is precisely the "wrap each repeated block" form the
suggestion prescribes; without one, the whole forward is wrapped under
the policy. ``remat_applied`` counts every build that actually wrapped,
and the chosen policy is surfaced via the ``remat_policy`` extra in
``mx.obs.report()``'s counters companion gauges.
"""
from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

from .base import MXNetError

__all__ = ["resolve_policy"]

log = logging.getLogger(__name__)


def _policy_by_name(name: str):
    import jax
    pol = getattr(jax.checkpoint_policies, name, None)
    if pol is None or name.startswith("_"):
        valid = sorted(p for p in dir(jax.checkpoint_policies)
                       if not p.startswith("_"))
        raise MXNetError(
            "MXNET_TPU_REMAT=%r is not a jax.checkpoint_policies name; "
            "valid policies: %s (or off/auto)" % (name, ", ".join(valid)))
    return pol


def resolve_policy(symbol=None, input_shapes=None, input_dtypes=None
                   ) -> Tuple[Optional[Any], str]:
    """Resolve the active remat policy for a bind: ``(policy, name)``,
    where ``policy`` is a jax saveable-predicate (None = remat off).
    ``auto`` consumes the analyzer's suggestion for ``symbol`` directly;
    it needs the bound shapes to rank candidates."""
    from . import config as _config
    mode = _config.get("MXNET_TPU_REMAT")
    if mode == "off":
        if _config.get("MXNET_EXEC_ENABLE_REMAT"):
            # legacy alias (docs/env_var.md): the historical fused-step
            # save-policy form
            name = "dots_with_no_batch_dims_saveable"
            return _policy_by_name(name), name
        return None, "off"
    if mode != "auto":
        return _policy_by_name(mode), mode
    if symbol is None:
        return None, "off"
    from .analysis import analyze_symbol
    # only the policy NAME is consumed here; skip the pass's concrete
    # block-residual calibration (the audit CLI / round-trip test ask
    # for it explicitly)
    report = analyze_symbol(symbol, input_shapes=input_shapes,
                            input_dtypes=input_dtypes,
                            context="remat-auto", calibrate_remat=False)
    remat = report.extras.get("remat") or {}
    suggestion = remat.get("suggestion") or {}
    name = suggestion.get("policy")
    if not name:
        log.info("MXNET_TPU_REMAT=auto: remat-opportunity found nothing "
                 "worth rematerializing; running without checkpoint")
        return None, "off"
    return _policy_by_name(name), "auto:%s" % name

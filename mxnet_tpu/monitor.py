"""``mx.mon.Monitor`` — per-op output statistics during training.

Reference: ``python/mxnet/monitor.py:33`` — Monitor(interval, stat_func,
pattern, sort); ``install`` hooks the executor's monitor callback, ``tic``
arms collection for the coming batch, ``toc``/``toc_print`` drain the
queue. The executor tap is ``Executor.monitor_values`` (every node output,
the per-engine-op callback of the reference) filtered by ``pattern``.
"""
from __future__ import annotations

import re
from math import sqrt
from typing import Callable, List, Optional, Tuple

import numpy as np

__all__ = ["Monitor"]


class Monitor(object):
    """(reference: monitor.py:33)."""

    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        if stat_func is None:
            def stat_func(x):
                # |x|.mean() — the reference's default "asum/size" stat
                return np.abs(np.asarray(x)).mean()
        self.interval = interval
        self.stat_func = stat_func
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.queue: List[Tuple[int, str, object]] = []
        self.step = 0
        self.activated = False
        self.exes: List[object] = []

    def stat_helper(self, name, arr):
        """Executor callback (reference: monitor.py stat_helper)."""
        if not self.activated or not self.re_pattern.match(name):
            return
        if hasattr(arr, "asnumpy"):
            arr = arr.asnumpy()
        self.queue.append((self.step, name, self.stat_func(arr)))

    def install(self, exe, monitor_all: bool = True):
        """Attach to an executor (reference: monitor.py install).

        ``monitor_all=True`` (default) collects EVERY node's output via the
        executor's eager re-interpretation at ``toc`` time;
        ``monitor_all=False`` taps only the graph outputs through the
        forward-time callback. The two modes are exclusive so a stat is
        never reported twice for one tensor."""
        if not monitor_all:
            exe.set_monitor_callback(self.stat_helper)
        self.exes.append((exe, monitor_all))
        return exe

    def tic(self):
        """Arm collection if this step hits the interval (reference:
        monitor.py tic)."""
        if self.step % self.interval == 0:
            for exe, _ in self.exes:
                for arr in getattr(exe, "arg_arrays", []):
                    arr.wait_to_read()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self) -> List[Tuple[int, str, str]]:
        """Drain collected stats (reference: monitor.py toc)."""
        if not self.activated:
            return []
        for exe, monitor_all in self.exes:
            if monitor_all and hasattr(exe, "monitor_values"):
                for name, arr in exe.monitor_values():
                    self.stat_helper(name, arr)
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v in self.queue:
            res.append((n, k, str(v)))
        self.queue = []
        return res

    def toc_print(self):
        """(reference: monitor.py toc_print)."""
        for n, k, v in self.toc():
            print("Batch: %7d %30s %s" % (n, k, v))

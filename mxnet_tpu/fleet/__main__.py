"""Fleet process entry points.

::

    python -m mxnet_tpu.fleet replica --port P --rank R --model-json S
        One decode replica behind the fleet wire (the gateway's
        supervisor launches these; running one by hand is fine too).

    python -m mxnet_tpu.fleet serve --spec S [--replicas N] [--port P]
                                    [--metrics-port M]
        The gateway: supervises N replicas of the spec'd model, serves
        the client wire on --port (0 = ephemeral, announced on stdout)
        and the federated /metrics on --metrics-port. Implies
        MXNET_TPU_FLEET=1 — invoking the entry point IS the opt-in.

    python -m mxnet_tpu.fleet stats --address HOST:PORT
        One STATS round-trip against a gateway or replica, printed as
        JSON (the operator's curl).
"""
from __future__ import annotations

import json
import signal
import sys
import time


def _parse_address(s: str):
    host, _, port = s.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _run_serve(argv) -> int:
    import argparse
    parser = argparse.ArgumentParser(prog="mxnet_tpu.fleet serve")
    parser.add_argument("--spec", required=True,
                        help="replica model spec (JSON)")
    parser.add_argument("--replicas", type=int, default=None)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--metrics-port", type=int, default=None)
    args = parser.parse_args(argv)
    from .. import config as _config
    _config.set("MXNET_TPU_FLEET", True)    # the entry point IS the opt-in
    from .gateway import Gateway
    gw = Gateway(spec=json.loads(args.spec), replicas=args.replicas,
                 port=args.port, metrics_port=args.metrics_port)
    flags = {"stop": False}

    def _on_sig(_sig, _frm):                # flag-only handler
        flags["stop"] = True

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_sig)
        except (ValueError, OSError):
            pass
    print(json.dumps({"event": "ready", "port": gw.port,
                      "metrics_port": gw.metrics_port,
                      "replicas": len(gw._replicas)}), flush=True)
    while not flags["stop"]:
        time.sleep(0.2)
    gw.close(drain=True, timeout=30.0)
    return 0


def _run_stats(argv) -> int:
    import argparse
    parser = argparse.ArgumentParser(prog="mxnet_tpu.fleet stats")
    parser.add_argument("--address", required=True)
    args = parser.parse_args(argv)
    from . import wire as _wire
    snap = _wire.request_value(_parse_address(args.address), "STATS")
    json.dump(snap, sys.stdout, indent=2, sort_keys=True)
    print()
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "replica":
        from .replica import run_replica
        return run_replica(rest)
    if cmd == "serve":
        return _run_serve(rest)
    if cmd == "stats":
        return _run_stats(rest)
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main())

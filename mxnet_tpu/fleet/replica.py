"""Fleet replica: one decode server behind the fleet wire.

A replica process is a plain :class:`~mxnet_tpu.serve.server.
GenerativeServer` (built from a JSON model spec, deterministic seeded
init so every replica serves bit-identical weights — the fail-over
re-prefill contract requires it) fronted by :class:`~mxnet_tpu.fleet.
wire.ServeWire`. Respawns reach first token with zero backend compiles
through the PR 16 AOT path: the supervisor passes
``MXNET_TPU_COMPILE_CACHE`` through, so a warm restart deserializes
every serve executable instead of recompiling.

Also here: :class:`ScriptedDecodeServer`, a stdlib continuous-batching
*simulator* with the same ``submit_generate()/stats()/close()`` surface.
Its decode step is a timed wait, modeling the TPU regime where the
device does the work and the host idles between steps — it is what the
fleet bench scales against on a device-less CI box (the host-side
gateway/wire/scheduler stack is measured for real; only the device time
is simulated), and what the fleet unit tests drive so they never pay a
model build. Its token function is deterministic and autoregressive
(:func:`scripted_token`), so a re-prefilled continuation is bit-equal
to an uninterrupted stream — exactly the property the fail-over drill
asserts.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

from .. import lockcheck as _lockcheck
from .. import profiler as _profiler
from ..serve.server import (DeadlineExceeded, GenerateHandle, QueueFull,
                            ServerClosed)
from ..serve.stats import DecodeLatencyStats, monotonic
from .wire import ServeWire

__all__ = ["ScriptedDecodeServer", "ReplicaFront", "build_from_spec",
           "scripted_token", "run_replica"]


def scripted_token(seq: List[int]) -> int:
    """The scripted decoder's next token — a pure autoregressive
    function of the running sequence, so continuing from ``prompt +
    generated-prefix`` on a different replica reproduces the exact
    stream an uninterrupted decode would have produced."""
    return (31 * sum(seq) + 7) % 251


class _ScriptedSeq(object):
    __slots__ = ("handle", "seq", "generated", "max_new_tokens",
                 "eos_id", "t_submit", "t_last")

    def __init__(self, handle, seq, max_new_tokens, eos_id, t_submit):
        self.handle = handle
        self.seq = seq
        self.generated = 0
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.t_submit = t_submit
        self.t_last = monotonic()


class ScriptedDecodeServer(object):
    """Continuous-batching decode simulator (stdlib, no model).

    Faithful to the GenerativeServer scheduler's shape: admissions
    happen between decode steps (paying a per-token prefill cost that
    stalls the whole batch — the TTFT/TPOT tradeoff is real), one step
    advances every resident sequence by one token, finished sequences
    evict at step granularity. The step itself is a timed wait of
    ``step_s`` — simulated device time.
    """

    def __init__(self, slots: int = 4, step_s: float = 0.02,
                 prefill_s_per_token: float = 0.001,
                 queue_bound: int = 256, name: str = "fleet_scripted"):
        self.name = name
        self.max_sequences = int(slots)
        self.step_s = float(step_s)
        self.prefill_s_per_token = float(prefill_s_per_token)
        self.queue_bound = int(queue_bound)
        self.latency = DecodeLatencyStats(name=name)
        self._lock = _lockcheck.Lock(name="fleet.scripted_lock")
        self._cond = _lockcheck.Condition(self._lock)
        self._waiting: collections.deque = collections.deque()
        self._active: List[_ScriptedSeq] = []
        self._closed = False
        self._worker = threading.Thread(
            target=self._loop, daemon=True,
            name="mxnet_tpu.fleet.scripted[%s]" % name)
        self._worker.start()

    # ------------------------------------------------------------ submit
    def submit_generate(self, prompt, max_new_tokens: int = 32,
                        eos_id: Optional[int] = None,
                        timeout: Optional[float] = None,
                        temperature: float = 0.0,
                        seed: Optional[int] = None,
                        on_token=None) -> GenerateHandle:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        deadline = None if timeout is None else monotonic() + timeout
        handle = GenerateHandle(on_token=on_token)
        with self._cond:
            if self._closed:
                raise ServerClosed("submit_generate() after close()")
            if len(self._waiting) >= self.queue_bound:
                _profiler.incr_counter(self.name + "_shed")
                raise QueueFull("queue depth %d at admission bound %d"
                                % (len(self._waiting), self.queue_bound))
            self._waiting.append(
                (prompt, int(max_new_tokens), eos_id, deadline,
                 handle, monotonic()))
            _profiler.incr_counter(self.name + "_requests")
            self._cond.notify_all()
        return handle

    # --------------------------------------------------------- scheduler
    def _loop(self) -> None:
        while True:
            admitted = []
            with self._cond:
                while not self._waiting and not self._active \
                        and not self._closed:
                    self._cond.wait(0.05)
                if self._closed and not self._waiting \
                        and not self._active:
                    return
                while self._waiting \
                        and len(self._active) < self.max_sequences:
                    req = self._waiting.popleft()
                    admitted.append(req)
            prefill_wait = 0.0
            for prompt, max_new, eos_id, deadline, handle, t0 in admitted:
                if deadline is not None and monotonic() > deadline:
                    _profiler.incr_counter(
                        self.name + "_deadline_expired")
                    handle._finish(DeadlineExceeded(
                        "TTFT deadline expired in queue"))
                    continue
                prefill_wait += self.prefill_s_per_token * len(prompt)
                seq = _ScriptedSeq(handle, list(prompt), max_new, eos_id,
                                   t0)
                with self._lock:
                    self._active.append(seq)
            if prefill_wait > 0.0:
                time.sleep(prefill_wait)    # simulated prefill device time
            with self._lock:
                active = list(self._active)
            if not active:
                continue
            time.sleep(self.step_s)         # simulated decode-step time
            for seq in active:
                tok = scripted_token(seq.seq)
                seq.seq.append(tok)
                seq.generated += 1
                now = monotonic()
                if seq.generated == 1:
                    self.latency.ttft.record(now - seq.t_submit)
                else:
                    self.latency.tpot.record(now - seq.t_last)
                seq.t_last = now
                seq.handle._put(tok)
                _profiler.incr_counter(self.name + "_tokens")
                if seq.generated >= seq.max_new_tokens or \
                        (seq.eos_id is not None and tok == seq.eos_id) \
                        or seq.handle._cancelled:
                    with self._lock:
                        self._active.remove(seq)
                    seq.handle._finish(None)

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            active = len(self._active)
            waiting = len(self._waiting)
        return {
            "requests": _profiler.get_counter(self.name + "_requests"),
            "tokens": _profiler.get_counter(self.name + "_tokens"),
            "active_sequences": active,
            "waiting": waiting,
            "shed": _profiler.get_counter(self.name + "_shed"),
            "deadline_expired": _profiler.get_counter(
                self.name + "_deadline_expired"),
            "kv": {
                "slots_in_use": active,
                "max_slots": self.max_sequences,
                "occupancy": round(active / float(self.max_sequences), 4),
            },
            "ttft": self.latency.ttft.snapshot(),
            "tpot": self.latency.tpot.snapshot(),
        }

    # ------------------------------------------------------------- close
    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        with self._cond:
            self._closed = True
            if not drain:
                dropped = list(self._waiting)
                self._waiting.clear()
                for seq in self._active:
                    seq.handle._cancelled = True
            else:
                dropped = []
            self._cond.notify_all()
        for _p, _m, _e, _d, handle, _t in dropped:
            handle._finish(ServerClosed("server closed"))
        self._worker.join(timeout)


class ReplicaFront(object):
    """What the replica's wire actually fronts: the decode server plus
    the replica-identity surface — rank-labeled Prometheus exposition
    (the gateway's ``/metrics`` federates on the ``replica=<r>`` label)
    and a ``stats()`` superset carrying ``rank`` / ``pid`` /
    ``backend_compiles`` (the zero-compile-respawn drill reads the last
    one straight off the heartbeat)."""

    def __init__(self, server, rank: int):
        self.server = server
        self.rank = int(rank)

    def submit_generate(self, *args, **kwargs):
        return self.server.submit_generate(*args, **kwargs)

    def stats(self) -> Dict[str, Any]:
        snap = self.server.stats()
        snap["rank"] = self.rank
        snap["pid"] = os.getpid()
        snap["backend_compiles"] = self._backend_compiles()
        return snap

    def _backend_compiles(self) -> int:
        """Backend compiles attributed to this server's scope (the PR 16
        obs compile accounting) — 0 on an AOT-warm respawn."""
        try:
            from .. import obs as _obs
            rep = _obs.report()
            return len([c for c in rep.get("compiles", ())
                        if c.get("scope") == getattr(self.server, "name",
                                                     None)])
        except Exception:                                   # noqa: BLE001
            return -1               # accounting unavailable, not zero

    def metrics_text(self) -> str:
        from ..obs.prometheus import render_prometheus
        return render_prometheus(labels={"replica": str(self.rank)})

    def close(self, *args, **kwargs):
        return self.server.close(*args, **kwargs)


def build_from_spec(spec: Dict[str, Any]):
    """Build the replica's decode server from a JSON-able spec.

    ``{"kind": "transformer", "geo": {...}, "seed": 11, "slots": 4,
    "page": 8, "int8": false, "name": ...}`` builds a zoo transformer
    with deterministic seeded init (identical weights on every replica
    — the fail-over contract) and wraps it in a GenerativeServer;
    ``{"kind": "scripted", "slots": 4, "step_ms": 20, ...}`` builds the
    device-time simulator.
    """
    kind = spec.get("kind", "transformer")
    name = spec.get("name", "fleet_replica")
    if kind == "scripted":
        return ScriptedDecodeServer(
            slots=int(spec.get("slots", 4)),
            step_s=float(spec.get("step_ms", 20.0)) / 1e3,
            prefill_s_per_token=float(
                spec.get("prefill_ms_per_token", 1.0)) / 1e3,
            queue_bound=int(spec.get("queue_bound", 256)),
            name=name)
    if kind != "transformer":
        raise ValueError("unknown replica spec kind %r" % (kind,))
    import numpy as np
    from .. import context as _context
    from .. import initializer as _init
    from ..models import transformer as _transformer
    from ..module import Module
    from ..serve.server import GenerativeServer
    geo = dict(spec["geo"])
    net = _transformer.get_symbol(**geo)
    m = Module(net, context=_context.cpu())
    s = int(geo["seq_len"])
    m.bind(data_shapes=[("data", (1, s))],
           label_shapes=[("softmax_label", (1, s))])
    # initializers draw from global np.random: seeding it makes params
    # bit-identical across replica processes (serve_decode_smoke's AOT
    # drill relies on the same property)
    np.random.seed(int(spec.get("seed", 11)))
    m.init_params(_init.Uniform(0.05))
    return GenerativeServer(
        m, n_heads=int(geo["n_heads"]),
        max_sequences=spec.get("slots"),
        page=spec.get("page"), int8=spec.get("int8"),
        prefill_tokens=spec.get("prefill_tokens"),
        queue_bound=spec.get("queue_bound"),
        name=name)


def run_replica(argv: Optional[List[str]] = None) -> int:
    """``python -m mxnet_tpu.fleet replica`` body: build the spec'd
    server, front it with the wire, announce readiness on stdout, then
    park until QUIT or SIGTERM (flag-only handler — the elastic
    signal discipline)."""
    import argparse
    parser = argparse.ArgumentParser(prog="mxnet_tpu.fleet replica")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--rank", type=int, default=0)
    parser.add_argument("--model-json", default=None)
    parser.add_argument("--model-file", default=None)
    args = parser.parse_args(argv)
    if args.model_json:
        spec = json.loads(args.model_json)
    elif args.model_file:
        with open(args.model_file, "r", encoding="utf-8") as f:
            spec = json.load(f)
    else:
        parser.error("one of --model-json / --model-file is required")
    flags = {"stop": False}

    def _on_term(_sig, _frm):       # flag-only: nothing lock-taking
        flags["stop"] = True

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass                        # not the main thread (tests)
    server = build_from_spec(spec)
    front = ReplicaFront(server, rank=args.rank)
    wire = ServeWire(front, port=args.port, host=args.host,
                     rank=args.rank, fault_site="replica.die",
                     name="fleet.replica")
    wire.on_quit(lambda: flags.__setitem__("stop", True))
    print(json.dumps({"event": "ready", "rank": args.rank,
                      "port": wire.port, "pid": os.getpid()}),
          flush=True)
    while not flags["stop"]:
        time.sleep(0.2)
    wire.stop()
    server.close(drain=False, timeout=10.0)
    return 0

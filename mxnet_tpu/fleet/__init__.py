"""mxnet_tpu.fleet — multi-replica serving: gateway routing, replica
supervision, and fail-over for generative decode.

One :class:`Gateway` process fronts N ``GenerativeServer`` replica
processes over a stdlib line-protocol wire (``fleet.wire``, the
``dist.PodKVServer`` framing extended with streaming token frames):

* supervision — per-replica bounded-backoff respawn (the elastic
  discipline), PING liveness with the ProbeRing refused-vs-timeout
  rule, warm restarts through the AOT executable cache (zero backend
  compiles on respawn);
* routing + admission — sequences are sticky to the replica holding
  their KV pages; new requests go least-loaded (occupancy + queue
  depth from the heartbeat snapshots); the gateway sheds beyond its
  admission bound and propagates TTFT deadlines to the replica;
* fail-over — a replica death mid-stream re-prefills the victim's
  sequences on a survivor from the retained prompt + delivered prefix,
  with at-most-once delivery (frames dedup by emitted-token index);
  co-resident survivor sequences are untouched;
* federated obs — the gateway ``/metrics`` merges per-replica
  ``replica=<r>``-labeled expositions; replica blackboxes merge in
  ``python -m mxnet_tpu.obs blackbox``.

The package is lazy and opt-in: ``import mxnet_tpu`` never loads it,
and a :class:`Gateway` refuses to construct unless the
``MXNET_TPU_FLEET`` knob is set (spawning a subprocess fleet is a
deployment decision). ``python -m mxnet_tpu.fleet serve --spec ...``
is the process entry point.
"""
from .client import FleetClient
from .gateway import Gateway, merge_prometheus
from .replica import (ReplicaFront, ScriptedDecodeServer, build_from_spec,
                      run_replica, scripted_token)
from .wire import ServeWire, ping, probe, request_value, stream_generate

__all__ = [
    "Gateway", "FleetClient", "ServeWire", "ScriptedDecodeServer",
    "ReplicaFront", "build_from_spec", "run_replica", "scripted_token",
    "merge_prometheus", "ping", "probe", "request_value",
    "stream_generate",
]

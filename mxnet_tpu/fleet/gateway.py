"""Fleet gateway: replica supervision, routing/admission, fail-over.

One gateway process fronts N replica processes (each a
``GenerativeServer`` behind the fleet wire). The division of labor:

* **Supervision** — one supervisor thread per replica slot launches
  ``python -m mxnet_tpu.fleet replica`` with a deterministic model
  spec, waits for its first PING, then watches the process. Death means
  bounded-backoff respawn (:func:`mxnet_tpu.elastic.backoff_delay`, the
  training supervisor's exact formula) under the
  ``MXNET_TPU_FLEET_MAX_RESPAWNS`` budget. ``MXNET_TPU_COMPILE_CACHE``
  passes through, so a respawn warm-starts off the AOT executable cache
  and reaches first token with zero backend compiles.

* **Routing + admission** — a sequence is STICKY to the replica that
  prefilled it by construction: one GEN stream drives the whole
  generation on one connection, so every decode step lands on the
  replica holding its KV pages (migration happens only through the
  fail-over re-prefill below). New requests go to the least-loaded live
  replica, scored on the replica's heartbeat-reported KV occupancy and
  queue depth plus the gateway's own not-yet-reported assignment count
  (snapshots lag one heartbeat; the local term keeps a burst from
  dog-piling one replica). Admission beyond
  ``MXNET_TPU_FLEET_QUEUE_BOUND`` in-flight requests sheds with
  ``QueueFull``; the client's TTFT deadline rides the GEN payload so
  the replica can expire queued work (deadline propagation).

* **Fail-over** — a mid-stream replica death surfaces as a broken
  stream; a PING probe adjudicates (connection REFUSED = confirmed
  dead, timeout = ambiguous, the ProbeRing rule). The gateway retains
  every request's prompt and delivered-token prefix, re-prefills
  ``prompt + prefix`` on a survivor, and streams from global token
  index ``len(prefix)``. Delivery is at-most-once: a frame is forwarded
  iff its index equals the delivered count, so late or replayed frames
  drop (``fleet_dup_dropped``). Survivor-resident sequences are never
  touched — the victim's sequences arrive as fresh admissions at step
  granularity, the same continuous-batching join any new request makes.
  A replica's clean ``END`` distinguishes ``done`` (contract met, EOS,
  or KV-capacity truncation — a complete result, finished as a bare
  server would finish it) from ``released`` (a draining shutdown let
  go of an unfinished sequence — the remainder re-dispatches).
  Continuations are bit-equal for greedy decode; seeded sampling
  re-derives its seed from the fail-over point (deterministic, but a
  divergent sample path — see ``submit_generate``).

* **Federated obs** — ``/metrics`` merges the gateway's own registry
  with every live replica's ``replica=<r>``-labeled exposition
  (``render_prometheus(labels=)``); replica blackboxes inherit
  ``MXNET_TPU_OBS_BLACKBOX`` with ``MXNET_TPU_POD_RANK=<rank>`` so
  ``python -m mxnet_tpu.obs blackbox`` merges them post-mortem.
"""
from __future__ import annotations

import json
import os
import random as _pyrandom
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import config as _config
from .. import lockcheck as _lockcheck
from .. import profiler as _profiler
from ..base import MXNetError
from ..serve.server import (DeadlineExceeded, GenerateHandle, QueueFull,
                            ServeError, ServerClosed)
from ..serve.stats import DecodeLatencyStats, monotonic
from . import wire as _wire

__all__ = ["Gateway", "merge_prometheus"]


def merge_prometheus(texts: Sequence[str]) -> str:
    """Merge Prometheus expositions into one valid text: the first
    ``# HELP``/``# TYPE`` per metric name wins (the format allows
    metadata once), sample lines concatenate (replica-labeled samples
    are distinct series by construction)."""
    seen_meta = set()
    out: List[str] = []
    for text in texts:
        for line in text.splitlines():
            if line.startswith("# "):
                parts = line.split(" ", 3)
                key = tuple(parts[1:3]) if len(parts) >= 3 else (line,)
                if key in seen_meta:
                    continue
                seen_meta.add(key)
                out.append(line)
            elif line.strip():
                out.append(line)
    return "\n".join(out) + ("\n" if out else "")


class _Replica(object):
    """Gateway-side replica record. All fields are guarded by the
    gateway lock; ``generation`` fences late observations (a stream
    error from generation g must not mark generation g+1 dead)."""

    __slots__ = ("rank", "spec", "supervised", "addr", "proc",
                 "generation", "restarts", "state", "stats", "assigned",
                 "last_seen")

    def __init__(self, rank: int, spec=None, addr=None,
                 supervised: bool = True):
        self.rank = rank
        self.spec = spec
        self.supervised = supervised
        self.addr: Optional[Tuple[str, int]] = addr
        self.proc = None
        self.generation = 0
        self.restarts = 0
        self.state = "starting" if supervised else "live"
        self.stats: Dict[str, Any] = {}
        self.assigned = 0           # gateway streams currently on it
        self.last_seen = 0.0


class _FleetRequest(object):
    __slots__ = ("prompt", "max_new_tokens", "eos_id", "temperature",
                 "seed", "deadline", "handle", "delivered", "t_submit",
                 "t_first", "t_last")

    def __init__(self, prompt, max_new_tokens, eos_id, temperature,
                 seed, deadline, handle):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.temperature = temperature
        self.seed = seed
        self.deadline = deadline
        self.handle = handle
        self.delivered: List[int] = []  # the at-most-once dedup state
        self.t_submit = monotonic()
        self.t_first: Optional[float] = None
        self.t_last = self.t_submit


class Gateway(object):
    """Front N decode replicas; see the module docstring.

    Parameters
    ----------
    spec : dict, optional
        Replica model spec (:func:`~mxnet_tpu.fleet.replica.
        build_from_spec` grammar) — the gateway launches and supervises
        ``replicas`` subprocesses serving it.
    replicas : int, optional
        Supervised world size; default the ``MXNET_TPU_FLEET_REPLICAS``
        knob (env world discovery).
    addresses : list of (host, port), optional
        Front EXTERNALLY launched replicas instead of supervising own
        subprocesses (liveness then comes from the heartbeat poll
        alone). Mutually exclusive with ``spec``.
    port : int, optional
        Client-facing wire port (0 = ephemeral, read ``.port`` back);
        None = no wire, in-process ``submit_generate()`` only.
    metrics_port : int, optional
        Aggregated ``/metrics`` endpoint port; None = off.

    Requires the ``MXNET_TPU_FLEET`` knob: spawning a replica fleet is
    an explicit deployment decision, never a side effect.
    """

    def __init__(self, spec: Optional[Dict[str, Any]] = None,
                 replicas: Optional[int] = None,
                 addresses: Optional[Sequence[Tuple[str, int]]] = None,
                 name: str = "fleet", port: Optional[int] = 0,
                 metrics_port: Optional[int] = None,
                 queue_bound: Optional[int] = None,
                 stats_period: Optional[float] = None,
                 host: str = "127.0.0.1"):
        if not _config.get("MXNET_TPU_FLEET"):
            raise MXNetError(
                "the serving fleet is opt-in: set MXNET_TPU_FLEET=1 "
                "(or config.set) before constructing a Gateway — it "
                "spawns and supervises replica subprocesses")
        if (spec is None) == (addresses is None):
            raise ValueError("exactly one of spec= (supervised "
                             "subprocess replicas) or addresses= "
                             "(external replicas) is required")
        self.name = name
        self.queue_bound = int(
            queue_bound if queue_bound is not None
            else _config.get("MXNET_TPU_FLEET_QUEUE_BOUND"))
        self._stats_period = float(
            stats_period if stats_period is not None
            else _config.get("MXNET_TPU_FLEET_STATS_PERIOD"))
        self._spawn_timeout = float(
            _config.get("MXNET_TPU_FLEET_SPAWN_TIMEOUT"))
        self._max_respawns = int(
            _config.get("MXNET_TPU_FLEET_MAX_RESPAWNS"))
        self._backoff = float(_config.get("MXNET_TPU_ELASTIC_BACKOFF"))
        self._backoff_max = float(
            _config.get("MXNET_TPU_ELASTIC_BACKOFF_MAX"))
        self.latency = DecodeLatencyStats(name=name)
        self._lock = _lockcheck.Lock(name="fleet.gateway_lock")
        self._cond = _lockcheck.Condition(self._lock)
        self._closed = False        # no NEW submits
        self._closing = False       # tear the world down
        self._inflight = 0
        self._threads: List[threading.Thread] = []
        if addresses is not None:
            self._replicas = [
                _Replica(i, addr=(str(h), int(p)), supervised=False)
                for i, (h, p) in enumerate(addresses)]
        else:
            n = int(replicas if replicas is not None
                    else _config.get("MXNET_TPU_FLEET_REPLICAS"))
            if n < 1:
                raise ValueError("replicas must be >= 1")
            self._replicas = [_Replica(i, spec=dict(spec))
                              for i in range(n)]
            for rep in self._replicas:
                t = threading.Thread(
                    target=self._supervise, args=(rep,), daemon=True,
                    name="mxnet_tpu.fleet.sup[%d]" % rep.rank)
                t.start()
                self._threads.append(t)
        self._max_attempts = max(4, 2 * len(self._replicas) + 1)
        poller = threading.Thread(target=self._poll_loop, daemon=True,
                                  name="mxnet_tpu.fleet.stats_poll")
        poller.start()
        self._threads.append(poller)
        self._wire = None
        if port is not None:
            self._wire = _wire.ServeWire(self, port=port, host=host,
                                         name="fleet.gateway")
        self.port = self._wire.port if self._wire else None
        self._metrics = None
        if metrics_port is not None and metrics_port >= 0:
            from ..obs.http import MetricsServer
            self._metrics = MetricsServer(port=metrics_port,
                                          render=self.metrics_text)
        self.metrics_port = self._metrics.port if self._metrics else None

    # ------------------------------------------------------- supervision
    def _closing_now(self) -> bool:
        with self._lock:
            return self._closing

    def _live_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if r.state == "live")

    def _update_live_gauge(self) -> None:
        _profiler.set_gauge(self.name + "_replicas_live",
                            self._live_count())

    def _child_env(self, rep: _Replica,
                   first_spawn: bool) -> Dict[str, str]:
        env = dict(os.environ)
        # the replica must import THIS tree regardless of cwd
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        # blackbox files land as blackbox-p<rank>.jsonl so the obs
        # merger aligns replicas like pod ranks
        env["MXNET_TPU_POD_RANK"] = str(rep.rank)
        # a replica.die:hostkill must take down the REPLICA process
        # only — never adopt this gateway as a coordinated parent
        env.pop("MXNET_TPU_ELASTIC_COORDINATED", None)
        # faults armed in the gateway process must not leak into every
        # replica; the drill targets ONE rank explicitly:
        #   MXNET_TPU_FLEET_FAULT_REPLICA=<rank>:<fault spec>
        # and only that rank's FIRST spawn arms it — a respawned
        # generation must not re-fire its own killer (the data.worker
        # progress rule)
        env.pop("MXNET_TPU_FAULTS", None)
        target = os.environ.get("MXNET_TPU_FLEET_FAULT_REPLICA")
        if target and first_spawn:
            rank_s, _, fspec = target.partition(":")
            try:
                armed_rank = int(rank_s)
            except ValueError:
                armed_rank = -1
            if armed_rank == rep.rank and fspec:
                env["MXNET_TPU_FAULTS"] = fspec
        return env

    def _supervise(self, rep: _Replica) -> None:
        from .. import elastic as _elastic
        from ..parallel.dist import free_port
        rng = _pyrandom.Random(0x11E7 + rep.rank)
        first = True
        while True:
            with self._lock:
                if self._closing:
                    return
                rep.generation += 1
                rep.state = "starting"
                rep.addr = None
            port = free_port()
            addr = ("127.0.0.1", port)
            cmd = [sys.executable, "-m", "mxnet_tpu.fleet", "replica",
                   "--port", str(port), "--rank", str(rep.rank),
                   "--model-json", json.dumps(rep.spec)]
            proc = None
            try:
                proc = subprocess.Popen(
                    cmd, env=self._child_env(rep, first_spawn=first))
            except OSError:
                pass
            first = False
            ok = False
            if proc is not None:
                deadline = monotonic() + self._spawn_timeout
                while monotonic() < deadline and not self._closing_now():
                    if proc.poll() is not None:
                        break
                    if _wire.ping(addr, timeout=1.0):
                        ok = True
                        break
                    _elastic_sleep(0.1)
            if ok:
                with self._cond:
                    rep.proc = proc
                    rep.addr = addr
                    rep.state = "live"
                    self._cond.notify_all()
                self._update_live_gauge()
                while not self._closing_now():
                    try:
                        proc.wait(timeout=0.5)
                        break
                    except subprocess.TimeoutExpired:
                        continue
                if self._closing_now():
                    self._shutdown_child(proc, addr)
                    return
                with self._cond:
                    rep.state = "dead"
                    rep.addr = None
                    self._cond.notify_all()
                _profiler.incr_counter(self.name + "_replica_dead")
                self._update_live_gauge()
            elif proc is not None:
                try:
                    proc.kill()
                    proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
                with self._cond:
                    rep.state = "dead"
                    self._cond.notify_all()
            if self._closing_now():
                return
            rep.restarts += 1
            if rep.restarts > self._max_respawns:
                with self._cond:
                    rep.state = "failed"
                    self._cond.notify_all()
                return
            _profiler.incr_counter(self.name + "_respawn")
            delay = _elastic.backoff_delay(
                rep.restarts, self._backoff, self._backoff_max, rng=rng)
            end = monotonic() + delay
            while monotonic() < end:
                if self._closing_now():
                    return
                _elastic_sleep(0.1)

    def _shutdown_child(self, proc, addr) -> None:
        """Graceful replica shutdown ladder: QUIT -> SIGTERM -> SIGKILL,
        every wait bounded (PhaseGuard discipline)."""
        if addr is not None:
            try:
                _wire.request_value(addr, "QUIT", timeout=2.0)
            except OSError:
                pass
        for grace, escalate in ((5.0, proc.terminate), (3.0, proc.kill),
                                (10.0, None)):
            try:
                proc.wait(timeout=grace)
                return
            except subprocess.TimeoutExpired:
                if escalate is not None:
                    try:
                        escalate()
                    except OSError:
                        return

    # --------------------------------------------------------- heartbeat
    def _poll_loop(self) -> None:
        while True:
            with self._lock:
                if self._closing:
                    return
                targets = [(r, r.addr, r.generation)
                           for r in self._replicas if r.addr is not None]
            for rep, addr, gen in targets:
                try:
                    snap = _wire.request_value(
                        addr, "STATS",
                        timeout=max(1.0, self._stats_period))
                except ConnectionRefusedError:
                    # REFUSED is the probe-confirmed death signal; for
                    # supervised replicas the proc.wait() watcher is
                    # authoritative, so only external replicas flip here
                    with self._cond:
                        if rep.generation == gen \
                                and not rep.supervised \
                                and rep.state == "live":
                            rep.state = "dead"
                            self._cond.notify_all()
                    self._update_live_gauge()
                    continue
                except OSError:
                    continue        # ambiguous (timeout): never kill
                with self._cond:
                    if rep.generation == gen:
                        rep.stats = snap
                        rep.last_seen = monotonic()
                        if not rep.supervised and rep.state != "live":
                            rep.state = "live"
                            self._cond.notify_all()
                self._update_live_gauge()
            end = monotonic() + self._stats_period
            while monotonic() < end:
                if self._closing_now():
                    return
                _elastic_sleep(0.05)

    # ------------------------------------------------------------ submit
    def submit_generate(self, prompt, max_new_tokens: int = 32,
                        eos_id: Optional[int] = None,
                        timeout: Optional[float] = None,
                        temperature: float = 0.0,
                        seed: Optional[int] = None,
                        on_token=None) -> GenerateHandle:
        """Same contract as ``GenerativeServer.submit_generate`` — the
        fleet is a drop-in for a single server. ``timeout`` is the TTFT
        deadline and propagates to the serving replica (first-token
        admission only: once a token has been delivered, fail-over
        re-dispatch is not deadline-bounded).

        Determinism across fail-over: greedy decode
        (``temperature=0``) is bit-equal to an uninterrupted stream —
        the survivor re-prefills ``prompt + delivered-prefix`` and
        argmax depends only on the sequence. Seeded sampling
        (``temperature>0`` with ``seed``) is reproducible run-to-run
        but NOT bit-equal across a fail-over: the survivor's RNG
        cannot resume the dead replica's draw stream, so the
        continuation uses a seed derived from (seed, fail-over point)
        — deterministic, but a divergent sample path."""
        if hasattr(prompt, "asnumpy"):
            prompt = prompt.asnumpy()
        if hasattr(prompt, "tolist"):
            prompt = prompt.tolist()
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        deadline = None if timeout is None else monotonic() + timeout
        handle = GenerateHandle(on_token=on_token)
        req = _FleetRequest(prompt, int(max_new_tokens), eos_id,
                            float(temperature), seed, deadline, handle)
        with self._cond:
            if self._closed:
                raise ServerClosed("submit_generate() after close()")
            if self._inflight >= self.queue_bound:
                _profiler.incr_counter(self.name + "_shed")
                raise QueueFull(
                    "gateway at admission bound: %d in-flight"
                    % self._inflight)
            self._inflight += 1
            _profiler.set_gauge(self.name + "_inflight", self._inflight)
        _profiler.incr_counter(self.name + "_requests")
        t = threading.Thread(target=self._drive, args=(req,),
                             daemon=True, name="mxnet_tpu.fleet.req")
        t.start()
        return handle

    # ------------------------------------------------------------ driver
    def _finish(self, req: _FleetRequest,
                exc: Optional[BaseException]) -> None:
        with self._cond:
            self._inflight -= 1
            _profiler.set_gauge(self.name + "_inflight", self._inflight)
            self._cond.notify_all()
        req.handle._finish(exc)

    def _pick(self, excluded) -> Optional[_Replica]:
        """Least-loaded live replica (see module docstring for the
        score); fires the ``gateway.route`` fault site. Stickiness
        needs no table: the picked replica serves the whole stream, so
        KV-resident decode never migrates outside fail-over."""
        from .. import faults as _faults
        if _faults.ARMED:
            _faults.fire("gateway.route", default_kind="raise")
        with self._lock:
            cands = [r for r in self._replicas
                     if r.state == "live" and r.addr is not None
                     and r.rank not in excluded]
            if not cands:
                return None

            def score(r):
                st = r.stats or {}
                kv = st.get("kv") or {}
                slots = max(1, int(kv.get("max_slots", 1)))
                return ((r.assigned + int(st.get("waiting", 0)))
                        / float(slots)
                        + float(kv.get("occupancy", 0.0)))

            rep = min(cands, key=lambda r: (score(r), r.rank))
            rep.assigned += 1
            return rep

    def _stream_from(self, rep: _Replica, req: _FleetRequest):
        """One streaming attempt against one replica. None on success,
        else ``(verdict, exc)`` with verdict ``shed`` (retry elsewhere),
        ``died`` (fail-over), or ``fatal`` (surface to the caller)."""
        with self._lock:
            addr, gen = rep.addr, rep.generation
        if addr is None:
            return ("died", ConnectionResetError("replica restarting"))
        remaining = None
        if req.deadline is not None and not req.delivered:
            # the TTFT deadline constrains only the FIRST token (the
            # _drive guard): a fail-over re-dispatch after delivery
            # must not carry the expired deadline into the survivor's
            # admission, which would fail a request whose TTFT was
            # already satisfied
            remaining = max(0.05, req.deadline - monotonic())
        seed = req.seed
        if seed is not None and req.delivered:
            # a survivor's RNG restarts at draw 0, so a seeded
            # temperature>0 continuation cannot replay the dead
            # replica's draw stream; deriving the continuation seed
            # from the fail-over point keeps the re-dispatched stream
            # deterministic (same prefix -> same continuation) instead
            # of silently reusing draws 0..k at the wrong token
            # positions. Greedy decode stays bit-equal either way.
            seed = (int(seed)
                    ^ (0x9E3779B97F4A7C15 * len(req.delivered))) \
                & ((1 << 63) - 1)
        payload = {
            "prompt": req.prompt,
            "prefix": list(req.delivered),
            "start": len(req.delivered),
            "max_new_tokens": req.max_new_tokens - len(req.delivered),
            "eos_id": req.eos_id,
            "temperature": req.temperature,
            "seed": seed,
            "timeout": remaining,
        }

        def on_frame(idx: int, tok: int) -> None:
            if idx == len(req.delivered):
                req.delivered.append(tok)
                now = monotonic()
                if req.t_first is None:
                    req.t_first = now
                    self.latency.ttft.record(now - req.t_submit)
                else:
                    self.latency.tpot.record(now - req.t_last)
                req.t_last = now
                _profiler.incr_counter(self.name + "_tokens")
                req.handle._put(tok)
            else:
                # a frame from a past life of this request (the dying
                # replica raced the fail-over): at-most-once = drop
                _profiler.incr_counter(self.name + "_dup_dropped")

        try:
            end = _wire.stream_generate(addr, payload, on_frame)
            done = (len(req.delivered) >= req.max_new_tokens
                    or (req.eos_id is not None and req.delivered
                        and req.delivered[-1] == req.eos_id))
            if not done and isinstance(end, dict) \
                    and end.get("reason", "released") == "released":
                # the replica let go of an UNfinished sequence (a
                # draining shutdown cancels at a step boundary): the
                # remainder re-dispatches like a death. A short "done"
                # END is a COMPLETE result (KV-capacity truncation) —
                # a bare server finishes such a request, so we do too.
                return ("released", None)
            return None
        except (QueueFull, ServerClosed) as exc:
            return ("shed", exc)
        except DeadlineExceeded as exc:
            return ("fatal", exc)
        except ServeError as exc:
            return ("fatal", exc)
        except OSError as exc:
            self._note_stream_break(rep, gen, addr)
            return ("died", exc)

    def _note_stream_break(self, rep: _Replica, gen: int, addr) -> None:
        """A broken stream is only a SUSPICION; the PING probe
        adjudicates (refused = dead, PONG = alive, timeout/garbage =
        ambiguous — exactly the ProbeRing distinction)."""
        if _wire.probe(addr, timeout=1.0) != "dead":
            return
        with self._cond:
            if rep.generation == gen and rep.state == "live":
                rep.state = "dead"
                self._cond.notify_all()
        self._update_live_gauge()

    def _wait_any_live(self, timeout: float) -> bool:
        deadline = monotonic() + timeout
        with self._cond:
            while True:
                if any(r.state == "live" for r in self._replicas):
                    return True
                if self._closing:
                    return False
                if all(r.state == "failed" for r in self._replicas):
                    return False
                left = deadline - monotonic()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 0.2))

    def _drive(self, req: _FleetRequest) -> None:
        from .. import faults as _faults
        attempts = 0
        excluded: set = set()
        while True:
            if len(req.delivered) >= req.max_new_tokens or (
                    req.eos_id is not None and req.delivered
                    and req.delivered[-1] == req.eos_id):
                self._finish(req, None)     # died at the finish line
                return
            if req.deadline is not None and not req.delivered \
                    and monotonic() > req.deadline:
                _profiler.incr_counter(self.name + "_deadline_expired")
                self._finish(req, DeadlineExceeded(
                    "TTFT deadline expired before any replica answered"))
                return
            try:
                rep = self._pick(excluded)
            except (_faults.FaultInjected, OSError) as exc:
                self._finish(req, ServeError(
                    "injected fault at gateway.route killed this "
                    "request (%s); other requests unaffected" % exc))
                return
            if rep is None:
                if excluded:
                    # every live replica shed us: that IS the answer
                    self._finish(req, QueueFull(
                        "every live replica is at its admission bound"))
                    return
                # a supervised world heals on the respawn clock; an
                # unsupervised (addresses=) world can only revive via
                # the heartbeat, so don't make a caller wait a spawn
                # timeout for peers nobody is restarting
                if any(r.supervised for r in self._replicas):
                    grace = self._spawn_timeout
                else:
                    grace = max(2.0, 4 * self._stats_period)
                if req.deadline is not None and not req.delivered:
                    grace = min(grace, max(0.0,
                                           req.deadline - monotonic()))
                attempts += 1
                if attempts > self._max_attempts \
                        or not self._wait_any_live(grace):
                    self._finish(req, ServeError(
                        "no live replica (world down or respawn budget "
                        "exhausted)"))
                    return
                continue
            try:
                verdict = self._stream_from(rep, req)
            finally:
                with self._lock:
                    rep.assigned -= 1
            if verdict is None:
                # a "done" END: the replica finished the sequence on
                # its own terms — contract met, EOS, or KV-capacity
                # truncation. All are complete results (a bare server
                # finishes a truncated request short too; re-dispatch
                # would re-prefill past max_seq and fail it).
                self._finish(req, None)
                return
            kind, exc = verdict
            if kind == "fatal":
                if isinstance(exc, DeadlineExceeded):
                    _profiler.incr_counter(
                        self.name + "_deadline_expired")
                self._finish(req, exc)
                return
            attempts += 1
            if attempts > self._max_attempts:
                self._finish(req, ServeError(
                    "fail-over budget exhausted after %d attempts "
                    "(last: %s)" % (attempts,
                                    exc if exc is not None
                                    else "replica released the stream")))
                return
            if kind == "shed":
                _profiler.incr_counter(self.name + "_shed")
                excluded.add(rep.rank)
            else:
                # died (transport death) or released (the replica
                # cancelled an unfinished sequence while draining):
                # fail-over the remainder to a survivor
                _profiler.incr_counter(self.name + "_failover")
                excluded = set()    # dead rank is excluded via state

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            reps = [{
                "rank": r.rank, "state": r.state,
                "generation": r.generation, "restarts": r.restarts,
                "addr": list(r.addr) if r.addr else None,
                "assigned": r.assigned, "stats": r.stats,
            } for r in self._replicas]
            inflight = self._inflight
        return {
            "name": self.name,
            "live": sum(1 for r in reps if r["state"] == "live"),
            "inflight": inflight,
            "replicas": reps,
            "requests": _profiler.get_counter(self.name + "_requests"),
            "tokens": _profiler.get_counter(self.name + "_tokens"),
            "shed": _profiler.get_counter(self.name + "_shed"),
            "failover": _profiler.get_counter(self.name + "_failover"),
            "dup_dropped": _profiler.get_counter(
                self.name + "_dup_dropped"),
            "respawn": _profiler.get_counter(self.name + "_respawn"),
            "replica_dead": _profiler.get_counter(
                self.name + "_replica_dead"),
            "deadline_expired": _profiler.get_counter(
                self.name + "_deadline_expired"),
            "ttft": self.latency.ttft.snapshot(),
            "tpot": self.latency.tpot.snapshot(),
        }

    def metrics_text(self) -> str:
        """The federated exposition: this process's registry plus every
        live replica's ``replica=<r>``-labeled text."""
        from ..obs.prometheus import render_prometheus
        texts = [render_prometheus()]
        with self._lock:
            targets = [r.addr for r in self._replicas
                       if r.state == "live" and r.addr is not None]
        for addr in targets:
            try:
                texts.append(_wire.request_value(addr, "METRICS",
                                                 timeout=2.0))
            except OSError:
                pass                # a scrape never fails on one corpse
        return merge_prometheus(texts)

    def wait_ready(self, n: Optional[int] = None,
                   timeout: float = 300.0) -> int:
        """Block until ``n`` replicas (default: the whole world) are
        live; returns the live count (may be short on timeout)."""
        want = len(self._replicas) if n is None else int(n)
        deadline = monotonic() + timeout
        with self._cond:
            while True:
                live = sum(1 for r in self._replicas
                           if r.state == "live")
                if live >= want or self._closing:
                    return live
                left = deadline - monotonic()
                if left <= 0:
                    return live
                self._cond.wait(min(left, 0.2))

    # ------------------------------------------------------------- close
    def close(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop accepting; ``drain=True`` waits (bounded) for in-flight
        streams, then tears the replica world down gracefully."""
        with self._cond:
            already = self._closed
            self._closed = True
        if drain and not already:
            deadline = monotonic() + timeout
            with self._cond:
                while self._inflight > 0:
                    left = deadline - monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(min(left, 0.2))
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        if self._wire is not None:
            self._wire.stop()
        for t in self._threads:
            t.join(timeout=max(15.0, self._spawn_timeout / 4.0))
        if self._metrics is not None:
            self._metrics.close()
            self._metrics = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))
        return False


def _elastic_sleep(seconds: float) -> None:
    time.sleep(seconds)

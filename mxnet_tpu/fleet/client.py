"""Fleet client: the ``submit_generate`` contract over the wire.

``FleetClient`` points at a gateway (or, identically, a bare replica —
both fronts speak the same protocol) and hands out the same
:class:`~mxnet_tpu.serve.server.GenerateHandle` a local
``GenerativeServer`` would: iterate it for streaming, ``result()`` for
the whole sequence, and the serve exception taxonomy (``QueueFull``,
``DeadlineExceeded``, ``ServerClosed``) re-raises rehydrated from ERR
frames. Code written against a local server moves behind a fleet by
changing one constructor.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union
import threading

from ..serve.server import GenerateHandle
from . import wire as _wire

__all__ = ["FleetClient"]


class FleetClient(object):
    def __init__(self, address: Union[str, Tuple[str, int]],
                 connect_timeout: float = _wire._CONNECT_TIMEOUT,
                 stream_timeout: float = _wire._STREAM_TIMEOUT):
        # accept "host:port" too — indexing a string would otherwise
        # build the silently-wrong address ("1", 2) out of "127.0.0.1:p"
        if isinstance(address, (str, bytes)):
            host, _, port = str(address).rpartition(":")
            if not host or not port.isdigit():
                raise ValueError(
                    "FleetClient address string must be 'host:port', got %r"
                    % (address,))
            address = (host, int(port))
        self.address = (str(address[0]), int(address[1]))
        self.connect_timeout = float(connect_timeout)
        self.stream_timeout = float(stream_timeout)

    def ping(self, timeout: float = 1.0) -> bool:
        return _wire.ping(self.address, timeout=timeout)

    def stats(self) -> Dict[str, Any]:
        return _wire.request_value(self.address, "STATS")

    def metrics_text(self) -> str:
        return _wire.request_value(self.address, "METRICS")

    def submit_generate(self, prompt, max_new_tokens: int = 32,
                        eos_id: Optional[int] = None,
                        timeout: Optional[float] = None,
                        temperature: float = 0.0,
                        seed: Optional[int] = None,
                        on_token=None) -> GenerateHandle:
        """Non-blocking submit; a daemon thread drives the wire stream
        into the returned handle. Transport death surfaces as the
        handle's error (the gateway behind the wire already did its own
        fail-over — an error here means the GATEWAY died)."""
        if hasattr(prompt, "asnumpy"):
            prompt = prompt.asnumpy()
        if hasattr(prompt, "tolist"):
            prompt = prompt.tolist()
        payload = {
            "prompt": [int(t) for t in prompt],
            "prefix": [],
            "start": 0,
            "max_new_tokens": int(max_new_tokens),
            "eos_id": eos_id,
            "temperature": float(temperature),
            "seed": seed,
            "timeout": timeout,
        }
        handle = GenerateHandle(on_token=on_token)

        def drive() -> None:
            def on_frame(idx: int, tok: int) -> None:
                handle._put(tok)

            try:
                _wire.stream_generate(
                    self.address, payload, on_frame,
                    connect_timeout=self.connect_timeout,
                    stream_timeout=self.stream_timeout)
            except BaseException as exc:                    # noqa: BLE001
                handle._finish(exc)
            else:
                handle._finish(None)

        t = threading.Thread(target=drive, daemon=True,
                             name="mxnet_tpu.fleet.client")
        t.start()
        return handle

    def generate(self, prompt, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None,
                 temperature: float = 0.0,
                 seed: Optional[int] = None,
                 result_timeout: Optional[float] = None) -> List[int]:
        """Blocking convenience: the full token list (or the serve
        exception)."""
        handle = self.submit_generate(
            prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
            timeout=timeout, temperature=temperature, seed=seed)
        return handle.result(timeout=result_timeout)

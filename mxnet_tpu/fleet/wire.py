"""The fleet line protocol — ``dist.PodKVServer`` framing, extended
with streaming token frames.

One UTF-8 line per message, space-separated fields, structured payloads
as base64(JSON) so a payload can never smuggle a newline into the
framing (the PodKV rule). Stdlib-only on both sides.

Request lines (client -> server, one request per connection — the
PodKVClient discipline: no connection state to resynchronize after a
peer death)::

    PING                        -> PONG
    STATS                       -> VAL <b64 json>
    METRICS                     -> VAL <b64 text>     (Prometheus text)
    QUIT                        -> OK                 (then drain+exit)
    GEN <b64 json>              -> streaming frames, see below

``GEN`` replies are a frame stream on the same connection::

    TOK <idx> <token>           one frame per generated token; ``idx``
                                is the sequence-global emitted-token
                                index (prefix tokens already delivered
                                in an earlier life of the request are
                                NOT re-sent — ``idx`` starts at the
                                request's ``start``), the at-most-once
                                dedup key
    END <b64 json>              the stream finished ({"n": count,
                                "reason": "done"|"released"}).
                                ``done``: the server finished the
                                sequence on its own terms (contract
                                met, EOS, or KV-capacity truncation —
                                a complete result); ``released``: the
                                server let go of an UNfinished
                                sequence (a draining shutdown cancels
                                at a step boundary — the gateway
                                re-dispatches the remainder)
    ERR <b64 json>              {"kind": shed|deadline|closed|error,
                                 "msg": ...} — ``kind`` tells the
                                gateway whether to retry elsewhere
                                (shed/closed) or fail the request

The ``GEN`` payload: ``{"prompt": [...], "prefix": [...], "start": n,
"max_new_tokens": m, "eos_id": e|null, "temperature": t, "seed":
s|null, "timeout": ttft_seconds|null}``. ``prefix``/``start`` carry the
fail-over contract: a re-dispatched request prefills ``prompt+prefix``
on the survivor and streams from global index ``start``.
"""
from __future__ import annotations

import base64
import json
import socket
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from .. import lockcheck as _lockcheck
from ..serve.server import (DeadlineExceeded, GenerateHandle, QueueFull,
                            ServeError, ServerClosed)

__all__ = ["ServeWire", "stream_generate", "request_value", "ping",
           "probe", "dumps_b64", "loads_b64"]

_CONNECT_TIMEOUT = 5.0
# a healthy stream's inter-frame gap is bounded by one decode step; a
# dead peer's socket RSTs/EOFs almost immediately — this long timeout
# only catches a wedged-but-alive peer
_STREAM_TIMEOUT = 300.0


def dumps_b64(obj: Any) -> str:
    return base64.b64encode(
        json.dumps(obj, separators=(",", ":")).encode("utf-8")
    ).decode("ascii")


def loads_b64(s: str) -> Any:
    return json.loads(base64.b64decode(s.encode("ascii")).decode("utf-8"))


def _exc_kind(exc: BaseException) -> str:
    if isinstance(exc, QueueFull):
        return "shed"
    if isinstance(exc, DeadlineExceeded):
        return "deadline"
    if isinstance(exc, ServerClosed):
        return "closed"
    return "error"


def kind_to_exc(payload: Dict[str, Any]) -> ServeError:
    """Rehydrate an ERR frame into the serve exception taxonomy so
    fleet callers catch the same classes as local serve callers."""
    kind = payload.get("kind", "error")
    msg = str(payload.get("msg", "replica error"))
    if kind == "shed":
        return QueueFull(msg)
    if kind == "deadline":
        return DeadlineExceeded(msg)
    if kind == "closed":
        return ServerClosed(msg)
    return ServeError(msg)


class ServeWire(object):
    """TCP front for anything with the ``submit_generate()/stats()``
    shape — a ``GenerativeServer`` in a replica process, the scripted
    decode simulator, or the ``Gateway`` itself (the client-facing
    port speaks the same protocol, so ``FleetClient`` cannot tell a
    gateway from a bare replica).

    ``fault_site`` (replicas pass ``"replica.die"``) arms a fault check
    after every emitted token frame — the deterministic
    kill-mid-stream drill hook. The gateway front passes ``None``.
    """

    def __init__(self, target, port: int = 0, host: str = "127.0.0.1",
                 rank: Optional[int] = None,
                 fault_site: Optional[str] = None,
                 name: str = "fleet.wire"):
        self.target = target
        self.rank = rank
        self.fault_site = fault_site
        self.name = name
        self._lock = _lockcheck.Lock(name="fleet.wire_lock")
        self._stopped = False
        self._on_quit: Optional[Callable[[], None]] = None
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host = host
        self.port = self._srv.getsockname()[1]
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="%s[:%d]" % (name, self.port))
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def on_quit(self, fn: Callable[[], None]) -> None:
        """Callback for a received QUIT (the replica main loop hooks
        its shutdown flag here)."""
        self._on_quit = fn

    def stop(self) -> None:
        """Close the listener. Idempotent; in-flight streams finish on
        their own connections."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        try:
            # shutdown BEFORE close — the PodKVServer rule: close()
            # alone leaves a concurrently accept()-blocked listener
            # alive in the kernel
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass

    # ------------------------------------------------------------ server
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return              # stop() closed the listener
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn) -> None:
        try:
            conn.settimeout(_STREAM_TIMEOUT)
            rfile = conn.makefile("r", encoding="utf-8", newline="\n")
            line = rfile.readline()
            parts = line.strip().split(" ", 1)
            op = parts[0] if parts and parts[0] else ""
            if op == "PING":
                conn.sendall(b"PONG\n")
            elif op == "STATS":
                snap = self.target.stats()
                conn.sendall(("VAL %s\n" % dumps_b64(snap))
                             .encode("ascii"))
            elif op == "METRICS":
                text = self._metrics_text()
                conn.sendall(("VAL %s\n" % dumps_b64(text))
                             .encode("ascii"))
            elif op == "QUIT":
                conn.sendall(b"OK\n")
                cb = self._on_quit
                if cb is not None:
                    cb()
            elif op == "GEN" and len(parts) == 2:
                self._serve_gen(conn, loads_b64(parts[1]))
            else:
                conn.sendall(b"ERR\n")
        except (OSError, ValueError):
            pass                    # peer died mid-request: its problem
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _metrics_text(self) -> str:
        fn = getattr(self.target, "metrics_text", None)
        if fn is not None:
            return fn()
        from ..obs.prometheus import render_prometheus
        labels = {"replica": str(self.rank)} if self.rank is not None \
            else None
        return render_prometheus(labels=labels)

    def _serve_gen(self, conn, payload: Dict[str, Any]) -> None:
        from .. import faults as _faults
        start = int(payload.get("start", 0))
        try:
            prompt = [int(t) for t in payload["prompt"]]
            prefix = [int(t) for t in payload.get("prefix") or ()]
            handle = self.target.submit_generate(
                prompt + prefix,
                max_new_tokens=int(payload["max_new_tokens"]),
                eos_id=payload.get("eos_id"),
                timeout=payload.get("timeout"),
                temperature=float(payload.get("temperature", 0.0)),
                seed=payload.get("seed"))
        except Exception as exc:                            # noqa: BLE001
            conn.sendall(("ERR %s\n" % dumps_b64(
                {"kind": _exc_kind(exc), "msg": str(exc)}))
                .encode("ascii"))
            return
        n = 0
        try:
            # iterating the handle streams tokens as they decode and
            # re-raises the sequence's error after the last good token
            for tok in handle:
                conn.sendall(("TOK %d %d\n" % (start + n, tok))
                             .encode("ascii"))
                n += 1
                if _faults.ARMED and self.fault_site is not None:
                    # the kill-mid-stream drill hook: fires AFTER the
                    # frame is on the wire, so the drill's token count
                    # is exact
                    _faults.fire(self.fault_site, default_kind="sigkill")
            # a cancelled handle ended because the server RELEASED the
            # sequence (draining shutdown), not because it finished —
            # the distinction tells the gateway whether a short stream
            # is a complete result (KV-capacity truncation, EOS) or a
            # remainder to re-dispatch
            reason = ("released" if getattr(handle, "_cancelled", False)
                      else "done")
            conn.sendall(("END %s\n" % dumps_b64(
                {"n": n, "reason": reason})).encode("ascii"))
        except OSError:
            # the caller vanished (gateway fail-over already re-routed,
            # or a client gave up): stop streaming, free the sequence
            handle.cancel()
        except Exception as exc:                            # noqa: BLE001
            try:
                conn.sendall(("ERR %s\n" % dumps_b64(
                    {"kind": _exc_kind(exc), "msg": str(exc)}))
                    .encode("ascii"))
            except OSError:
                pass


# --------------------------------------------------------------- client

def _connect(address: Tuple[str, int],
             timeout: float = _CONNECT_TIMEOUT):
    return socket.create_connection(address, timeout=timeout)


def ping(address: Tuple[str, int], timeout: float = 1.0) -> bool:
    """One PING round-trip. False on ANY failure — callers that need
    the dead/unreachable distinction (the probe rule) use
    :func:`probe` instead."""
    try:
        with _connect(address, timeout=timeout) as conn:
            conn.settimeout(timeout)
            conn.sendall(b"PING\n")
            return conn.makefile("r").readline().strip() == "PONG"
    except OSError:
        return False


def probe(address: Tuple[str, int], timeout: float = 1.0) -> str:
    """Liveness adjudication: one PING round-trip, returning
    ``"alive"`` (a PONG came back), ``"dead"`` (connection refused —
    the probe-confirmed death signal), or ``"ambiguous"`` (timeout,
    EOF, malformed reply — never grounds for a kill verdict). The
    ProbeRing refused-vs-timeout rule on the fleet wire."""
    try:
        with _connect(address, timeout=timeout) as conn:
            conn.settimeout(timeout)
            conn.sendall(b"PING\n")
            line = conn.makefile("r", encoding="utf-8").readline()
    except ConnectionRefusedError:
        return "dead"
    except OSError:
        return "ambiguous"
    return "alive" if line.strip() == "PONG" else "ambiguous"


def request_value(address: Tuple[str, int], op: str,
                  timeout: float = 5.0) -> Any:
    """One ``STATS``/``METRICS``/``QUIT`` round-trip; the decoded VAL
    payload (or True for OK). Raises OSError on transport failure —
    ``ConnectionRefusedError`` is the probe-confirmed-dead signal."""
    with _connect(address, timeout=timeout) as conn:
        conn.settimeout(timeout)
        conn.sendall((op + "\n").encode("ascii"))
        line = conn.makefile("r", encoding="utf-8").readline().strip()
    if line == "OK":
        return True
    parts = line.split(" ", 1)
    if parts[0] != "VAL" or len(parts) != 2:
        raise OSError("bad %s reply %r from %s:%d"
                      % (op, line, address[0], address[1]))
    return loads_b64(parts[1])


def stream_generate(address: Tuple[str, int], payload: Dict[str, Any],
                    on_token: Callable[[int, int], None],
                    connect_timeout: float = _CONNECT_TIMEOUT,
                    stream_timeout: float = _STREAM_TIMEOUT
                    ) -> Dict[str, Any]:
    """Drive one GEN request: ``on_token(idx, tok)`` per TOK frame;
    returns the END payload. Raises the rehydrated serve exception on
    an ERR frame and OSError on transport death (connection reset /
    EOF mid-stream — the fail-over trigger)."""
    with _connect(address, timeout=connect_timeout) as conn:
        conn.settimeout(stream_timeout)
        conn.sendall(("GEN %s\n" % dumps_b64(payload)).encode("ascii"))
        rfile = conn.makefile("r", encoding="utf-8", newline="\n")
        while True:
            line = rfile.readline()
            if not line:
                raise ConnectionResetError(
                    "stream from %s:%d ended without END"
                    % (address[0], address[1]))
            parts = line.strip().split(" ")
            if parts[0] == "TOK" and len(parts) == 3:
                on_token(int(parts[1]), int(parts[2]))
            elif parts[0] == "END" and len(parts) == 2:
                return loads_b64(parts[1])
            elif parts[0] == "ERR" and len(parts) == 2:
                raise kind_to_exc(loads_b64(parts[1]))
            else:
                raise OSError("bad stream frame %r" % line.strip())


# re-exported for fleet-internal use (GenerateHandle is the streaming
# future every fleet layer hands out — the serve contract, unchanged)
_HANDLE = GenerateHandle

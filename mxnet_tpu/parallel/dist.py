"""Multi-host runtime: process bootstrap + cross-process collectives.

Reference: the ps-lite runtime (SURVEY.md §2.12) — ``src/kvstore/
kvstore_dist.h:50-320`` workers push/pull against server processes spawned by
``tools/launch.py``, wired together by DMLC_* environment variables
(``DMLC_PS_ROOT_URI``, ``DMLC_PS_ROOT_PORT``, ``DMLC_NUM_WORKER``,
``DMLC_WORKER_ID``, ``DMLC_ROLE``).

TPU design: there are no server processes. Every process is a worker running
the same SPMD program; ``jax.distributed.initialize`` is the rendezvous
(scheduler) and cross-host reduction is an XLA collective over a one-
device-per-process mesh — DCN/gloo between hosts, ICI within a slice. The
launcher keeps the reference's env protocol so `tools/launch.py -n N cmd`
works unchanged.

This module is the only place that talks to ``jax.distributed``; kvstore's
``dist_*`` types and ``gluon.Trainer`` build on it.
"""
from __future__ import annotations

import os
from functools import partial
from typing import Optional

__all__ = ["initialize", "is_initialized", "cluster_env", "rank",
           "num_workers", "allreduce_sum", "broadcast", "barrier",
           "heartbeat_start", "heartbeat_stop", "num_dead_nodes"]

_INITIALIZED = False
_COMM = None          # (mesh, local_device) cache
_FN_CACHE = {}


def cluster_env() -> Optional[dict]:
    """Parse the launcher's DMLC_* env protocol; None when not under a
    launcher (reference: ps-lite postoffice reads the same variables)."""
    uri = os.environ.get("DMLC_PS_ROOT_URI")
    port = os.environ.get("DMLC_PS_ROOT_PORT")
    n = os.environ.get("DMLC_NUM_WORKER")
    wid = os.environ.get("DMLC_WORKER_ID")
    if uri is None or port is None or n is None or wid is None:
        return None
    return {"coordinator": "%s:%s" % (uri, port),
            "num_workers": int(n), "rank": int(wid)}


def is_initialized() -> bool:
    return _INITIALIZED


def coordination_active() -> bool:
    """True when a jax.distributed coordination client exists (a pure state
    probe — never initializes a backend)."""
    try:
        from jax._src import distributed as _jdist
        return getattr(_jdist.global_state, "client", None) is not None
    except Exception:
        return False


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Join the cluster (idempotent). Arguments default to the DMLC_* env.

    Must run before any backend is initialized in this process — the global
    device view and the gloo/DCN collectives are fixed at backend creation.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    try:
        from jax._src import distributed as _jdist
        if getattr(_jdist.global_state, "client", None) is not None:
            _INITIALIZED = True   # user already ran jax.distributed.initialize
            return
    except Exception:
        pass
    env = cluster_env()
    if coordinator_address is None and env is not None:
        coordinator_address = env["coordinator"]
        num_processes = env["num_workers"]
        process_id = env["rank"]
    if coordinator_address is None:
        raise RuntimeError(
            "distributed init needs a coordinator: run under tools/launch.py "
            "(sets DMLC_PS_ROOT_URI/PORT, DMLC_NUM_WORKER, DMLC_WORKER_ID) "
            "or pass coordinator_address/num_processes/process_id")
    import jax
    from jax._src import xla_bridge
    if xla_bridge.backends_are_initialized():
        raise RuntimeError(
            "a jax backend is already initialized; distributed rendezvous "
            "must happen first (create the dist kvstore before touching "
            "devices)")
    try:
        # multi-process CPU collectives ride gloo; TPU backends ignore this
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _INITIALIZED = True


def rank() -> int:
    # authoritative: the coordination-service state (jax.process_index()
    # reads the *default backend*, which may be a single-chip view)
    try:
        from jax._src import distributed as _jdist
        if getattr(_jdist.global_state, "client", None) is not None:
            return _jdist.global_state.process_id or 0
    except Exception:
        pass
    import jax
    try:
        return jax.process_index()
    except Exception:
        return 0


def num_workers() -> int:
    try:
        from jax._src import distributed as _jdist
        if getattr(_jdist.global_state, "client", None) is not None:
            return _jdist.global_state.num_processes or 1
    except Exception:
        pass
    import jax
    try:
        return jax.process_count()
    except Exception:
        return 1


def _comm():
    """One-device-per-process mesh for cross-process reductions.

    Prefers the default backend (a TPU slice spans all processes natively);
    falls back to the CPU backend, whose gloo collectives span hosts when
    ``initialize`` ran first.
    """
    global _COMM
    if _COMM is not None:
        return _COMM
    import numpy as np
    import jax
    from jax.sharding import Mesh

    n = num_workers()

    def pick(devs):
        by_proc = {}
        for d in devs:
            by_proc.setdefault(d.process_index, d)
        if len(by_proc) < n:
            return None
        return [by_proc[i] for i in range(n)]

    devs = pick(jax.devices())
    if devs is None:
        devs = pick(jax.devices("cpu"))
    if devs is None:
        raise RuntimeError(
            "no backend spans all %d processes — was dist.initialize() "
            "called before the first device access?" % n)
    mesh = Mesh(np.array(devs), ("proc",))
    local = devs[rank()]
    _COMM = (mesh, local)
    return _COMM


def _psum_fn(shape, dtype):
    key = ("psum", shape, str(dtype))
    fn = _FN_CACHE.get(key)
    if fn is None:
        import jax
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        mesh, _ = _comm()
        shard = partial(shard_map, mesh=mesh, in_specs=P("proc"),
                        out_specs=P())
        fn = jax.jit(shard(lambda s: jax.lax.psum(s[0], "proc")))
        _FN_CACHE[key] = fn
    return fn


def allreduce_sum(x):
    """Sum an identically-shaped per-process array across all processes;
    returns the reduction as a local jax array (replicated semantics)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    n = num_workers()
    if n == 1:
        return jnp.asarray(x)
    mesh, local = _comm()
    xl = jax.device_put(jnp.asarray(x), local)
    garr = jax.make_array_from_single_device_arrays(
        (n,) + xl.shape, NamedSharding(mesh, P("proc")), [xl[None]])
    out = _psum_fn(xl.shape, xl.dtype)(garr)
    return out.addressable_data(0)


def broadcast(x, root: int = 0):
    """Every process gets ``root``'s value (psum of one-hot contribution)."""
    import jax.numpy as jnp
    if num_workers() == 1:
        return jnp.asarray(x)
    contrib = jnp.asarray(x) if rank() == root else jnp.zeros_like(
        jnp.asarray(x))
    return allreduce_sum(contrib)


def barrier():
    """Block until every process reaches this point."""
    import jax
    if num_workers() == 1:
        return
    jax.block_until_ready(allreduce_sum(jax.numpy.zeros((1,))))


# ------------------------------------------------------- failure detection


def _client():
    import jax._src.distributed as _jdist
    return getattr(_jdist.global_state, "client", None)


_hb_started = False
_hb_stop = None           # threading.Event for the publisher thread
_hb_thread = None
# reader-side observations: rank -> (last counter, local time first seen)
_hb_seen = {}


def heartbeat_start(period: float = 5.0) -> bool:
    """Publish this worker's liveness to the coordinator's key-value store
    every ``period`` seconds (reference: ps-lite worker heartbeats to the
    scheduler, feeding kvstore.h:287 get_num_dead_node). The payload is a
    monotonically increasing beat COUNTER, not a wall-clock timestamp —
    staleness is judged on the reader's own clock, so cross-host clock
    skew cannot fake deaths. Idempotent; returns False when no
    coordination client exists (single process)."""
    global _hb_started, _hb_stop, _hb_thread
    import logging
    import threading
    client = _client()
    if client is None:
        return False
    if _hb_started:
        return True
    _hb_started = True
    _hb_stop = threading.Event()

    me = "mxnet_hb/%d" % rank()
    stop = _hb_stop

    def beat():
        n = 0
        warned = False
        while not stop.is_set():
            n += 1
            try:
                try:
                    client.key_value_set(me, str(n), allow_overwrite=True)
                except TypeError:   # older jaxlib: no overwrite kwarg
                    try:
                        client.key_value_delete(me)
                    except Exception:
                        pass
                    client.key_value_set(me, str(n))
                warned = False      # recovered: re-arm the warning
            except Exception as exc:
                # transient coordinator hiccups must not kill the beat —
                # a dead thread would report this live worker dead forever
                if not warned:
                    logging.warning("heartbeat publish failed "
                                    "(will keep retrying): %s", exc)
                    warned = True
            stop.wait(period)

    _hb_thread = threading.Thread(target=beat, daemon=True,
                                  name="mxnet-heartbeat")
    _hb_thread.start()
    return True


def heartbeat_stop(timeout: float = 2.0):
    """Stop the publisher thread (e.g. before a deliberate clean exit, so
    peers' ``get_num_dead_node`` sees this worker as *gone* rather than
    freshly-beating). Idempotent."""
    global _hb_started, _hb_stop, _hb_thread
    if _hb_stop is not None:
        _hb_stop.set()
    if _hb_thread is not None:
        _hb_thread.join(timeout)
    _hb_started, _hb_stop, _hb_thread = False, None, None


def num_dead_nodes(stale_after: float = 20.0, timeout_ms: int = 1000) -> int:
    """Count workers whose heartbeat is missing, or whose beat counter has
    not advanced for ``stale_after`` seconds of the CALLER's clock (two
    observations are needed to declare staleness, so a first call never
    false-positives on a slow-but-alive worker)."""
    import time
    client = _client()
    if client is None:
        return 0
    dead = 0
    now = time.monotonic()
    for r in range(num_workers()):
        try:
            counter = int(client.blocking_key_value_get(
                "mxnet_hb/%d" % r, timeout_ms))
        except Exception:
            dead += 1               # never heartbeated within the timeout
            continue
        prev = _hb_seen.get(r)
        if prev is None or prev[0] != counter:
            _hb_seen[r] = (counter, now)
        elif now - prev[1] > stale_after:
            dead += 1
    return dead

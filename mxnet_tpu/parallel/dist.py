"""Multi-host runtime: process bootstrap + cross-process collectives.

Reference: the ps-lite runtime (SURVEY.md §2.12) — ``src/kvstore/
kvstore_dist.h:50-320`` workers push/pull against server processes spawned by
``tools/launch.py``, wired together by DMLC_* environment variables
(``DMLC_PS_ROOT_URI``, ``DMLC_PS_ROOT_PORT``, ``DMLC_NUM_WORKER``,
``DMLC_WORKER_ID``, ``DMLC_ROLE``).

TPU design: there are no server processes. Every process is a worker running
the same SPMD program; ``jax.distributed.initialize`` is the rendezvous
(scheduler) and cross-host reduction is an XLA collective over a one-
device-per-process mesh — DCN/gloo between hosts, ICI within a slice. The
launcher keeps the reference's env protocol so `tools/launch.py -n N cmd`
works unchanged.

This module is the only place that talks to ``jax.distributed``; kvstore's
``dist_*`` types and ``gluon.Trainer`` build on it.
"""
from __future__ import annotations

import os
from functools import partial
from typing import Callable, Dict, Iterable, List, Optional

__all__ = ["initialize", "is_initialized", "cluster_env", "rank",
           "num_workers", "allreduce_sum", "broadcast", "barrier",
           "heartbeat_start", "heartbeat_stop", "num_dead_nodes",
           "dead_ranks", "reset_liveness", "kv_set", "kv_get",
           "free_port", "BootstrapTimeout", "sharding_island",
           "PodKVServer", "PodKVClient", "ProbeRing", "probe_peer",
           "elect_leader", "set_kv_backend", "kv_backend_active"]


def sharding_island():
    """Canonical layout claims of the multi-host data plane (audited by
    ``analysis.sharding_passes.check_islands``): the cross-host gradient
    reduction runs over the SAME ``(data, fsdp)`` axes the batch shards
    over, and parameter residency follows the unified FSDP claim — drawn
    from the one SpecLayout so the audit reports zero cross-island
    disagreements."""
    from .layout import island_specs
    return "dist", island_specs("dist")


def free_port() -> int:
    """Probe a free TCP port (bind 0, read it back, release). The usual
    TOCTOU caveat applies — the pod rendezvous publishes the port and
    rebinds it moments later; ONE shared helper so any future
    hardening (retry, port ranges) lands everywhere at once.
    (tools/launch.py keeps a private copy: the launcher is deliberately
    stdlib-only and runs before the package is importable.)"""
    import socket
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port

_INITIALIZED = False
_COMM = None          # (mesh, local_device) cache
_FN_CACHE = {}


def cluster_env() -> Optional[dict]:
    """Parse the launcher's DMLC_* env protocol; None when not under a
    launcher (reference: ps-lite postoffice reads the same variables)."""
    uri = os.environ.get("DMLC_PS_ROOT_URI")
    port = os.environ.get("DMLC_PS_ROOT_PORT")
    n = os.environ.get("DMLC_NUM_WORKER")
    wid = os.environ.get("DMLC_WORKER_ID")
    if uri is None or port is None or n is None or wid is None:
        return None
    return {"coordinator": "%s:%s" % (uri, port),
            "num_workers": int(n), "rank": int(wid)}


def is_initialized() -> bool:
    return _INITIALIZED


def coordination_active() -> bool:
    """True when a jax.distributed coordination client exists (a pure state
    probe — never initializes a backend)."""
    try:
        from jax._src import distributed as _jdist
        return getattr(_jdist.global_state, "client", None) is not None
    except Exception:
        return False


class BootstrapTimeout(RuntimeError):
    """The pod never fully assembled within the bootstrap deadline. The
    message names the absent rank(s) when the roll-call could tell."""


def _rollcall(coordinator_address: str, n: int, process_id: int,
              deadline: float) -> None:
    """Pre-rendezvous liveness check on the coordinator port, BEFORE
    jax.distributed binds it: every rank proves it is up, so a missing
    peer produces an error NAMING THE ABSENT RANK on every present rank
    instead of N-1 opaque deadline errors (or, on older stacks, a hang).

    Protocol (rank 0 listens; peers connect-with-retry):
      peer -> "mxhb <rank>\\n";  rank 0 -> "ok\\n" once ALL ranks arrived,
      or "missing <r,...>\\n" + close at the deadline.
    Rank 0 releases the port before returning, then jax.distributed's
    coordination service binds it; peers' grpc connects retry until the
    service is up (bounded by initialization_timeout)."""
    import socket
    import time
    host, _, port_s = coordinator_address.rpartition(":")
    port = int(port_s)
    t_end = time.monotonic() + deadline
    if process_id == 0:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        conns = {}
        try:
            try:
                srv.bind(("", port))
            except OSError:
                # the port is already owned (a prior half-shutdown
                # coordination service): skip the roll-call, the jax
                # rendezvous deadline is the backstop
                srv.close()
                return
            srv.listen(n)
            while len(conns) < n - 1:
                left = t_end - time.monotonic()
                if left <= 0:
                    break
                srv.settimeout(min(left, 1.0))
                try:
                    conn, _addr = srv.accept()
                except socket.timeout:
                    continue
                try:
                    conn.settimeout(min(max(left, 0.1), 5.0))
                    line = conn.makefile("r").readline().strip()
                    if line.startswith("mxhb "):
                        conns[int(line.split()[1])] = conn
                    else:
                        conn.close()
                except (OSError, ValueError, IndexError):
                    conn.close()
            missing = sorted(set(range(1, n)) - set(conns))
            reply = b"ok\n" if not missing else \
                ("missing %s\n" % ",".join(map(str, missing))).encode()
            for conn in conns.values():
                try:
                    conn.sendall(reply)
                except OSError:
                    pass
            if missing:
                raise BootstrapTimeout(
                    "pod bootstrap timed out after %.0fs: rank(s) %s of "
                    "world %d never connected to the coordinator (%s) — "
                    "check that every host launched its worker"
                    % (deadline, ",".join(map(str, missing)), n,
                       coordinator_address))
        finally:
            for conn in conns.values():
                try:
                    conn.close()
                except OSError:
                    pass
            srv.close()
        return
    # peers: connect with retry until the deadline
    while True:
        left = t_end - time.monotonic()
        if left <= 0:
            raise BootstrapTimeout(
                "pod bootstrap timed out after %.0fs: rank %d could not "
                "reach the coordinator (rank 0) at %s — is it up?"
                % (deadline, process_id, coordinator_address))
        try:
            conn = socket.create_connection((host or "127.0.0.1", port),
                                            timeout=min(left, 2.0))
        except OSError:
            time.sleep(min(left, 0.2))
            continue
        try:
            conn.settimeout(max(t_end - time.monotonic(), 0.1))
            conn.sendall(("mxhb %d\n" % process_id).encode())
            line = conn.makefile("r").readline().strip()
        except OSError:
            line = ""
        finally:
            conn.close()
        if line.startswith("missing"):
            raise BootstrapTimeout(
                "pod bootstrap failed: coordinator reports rank(s) %s of "
                "world %d never connected" % (line.split(None, 1)[1], n))
        # "ok" -> proceed; anything else (EOF, grpc noise) means rank 0 is
        # already past roll-call and the coordination service owns the
        # port — jax.distributed.initialize below is the backstop
        return


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               timeout: Optional[float] = None,
               retries: Optional[int] = None,
               rollcall: bool = True):
    """Join the cluster (idempotent). Arguments default to the DMLC_* env.

    Must run before any backend is initialized in this process — the global
    device view and the gloo/DCN collectives are fixed at backend creation.

    Bounded bootstrap: the rendezvous can never hang the pod forever — a
    roll-call on the coordinator port first proves every rank is up
    (failing with :class:`BootstrapTimeout` naming the absent rank), and
    ``jax.distributed.initialize`` itself runs under the same
    ``MXNET_TPU_DIST_TIMEOUT`` deadline with ``MXNET_TPU_DIST_RETRIES``
    bounded re-attempts for slow-but-alive peers.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    try:
        from jax._src import distributed as _jdist
        if getattr(_jdist.global_state, "client", None) is not None:
            _INITIALIZED = True   # user already ran jax.distributed.initialize
            return
    except Exception:
        pass
    env = cluster_env()
    if coordinator_address is None and env is not None:
        coordinator_address = env["coordinator"]
        num_processes = env["num_workers"]
        process_id = env["rank"]
    if coordinator_address is None:
        raise RuntimeError(
            "distributed init needs a coordinator: run under tools/launch.py "
            "(sets DMLC_PS_ROOT_URI/PORT, DMLC_NUM_WORKER, DMLC_WORKER_ID) "
            "or pass coordinator_address/num_processes/process_id")
    from .. import config as _config
    if timeout is None:
        timeout = float(_config.get("MXNET_TPU_DIST_TIMEOUT"))
    if retries is None:
        retries = max(0, int(_config.get("MXNET_TPU_DIST_RETRIES")))
    import jax
    from jax._src import xla_bridge
    if xla_bridge.backends_are_initialized():
        raise RuntimeError(
            "a jax backend is already initialized; distributed rendezvous "
            "must happen first (create the dist kvstore before touching "
            "devices)")
    try:
        # multi-process CPU collectives ride gloo; TPU backends ignore this
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    n = num_processes or 1
    last_exc: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            # the roll-call is INSIDE the retried window: "a
            # slow-starting peer gets one more window" must cover the
            # stage a slow peer actually fails at
            if rollcall and n > 1:
                _rollcall(coordinator_address, n, process_id or 0,
                          timeout)
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes, process_id=process_id,
                    initialization_timeout=max(1, int(timeout)))
            except TypeError:     # older jaxlib: no timeout kwarg
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes, process_id=process_id)
            _INITIALIZED = True
            return
        except Exception as exc:                           # noqa: BLE001
            last_exc = exc
            try:
                jax.distributed.shutdown()
            except Exception:                              # noqa: BLE001
                pass
            if attempt < retries:
                import logging
                logging.getLogger(__name__).warning(
                    "distributed rendezvous attempt %d/%d failed (%s); "
                    "retrying", attempt + 1, retries + 1, exc)
    raise BootstrapTimeout(
        "distributed rendezvous failed after %d attempt(s) x %.0fs "
        "(rank %s of %s via %s): %s — a peer is down or unreachable"
        % (retries + 1, timeout, process_id, num_processes,
           coordinator_address, last_exc)) from last_exc


def rank() -> int:
    # authoritative: the coordination-service state (jax.process_index()
    # reads the *default backend*, which may be a single-chip view)
    try:
        from jax._src import distributed as _jdist
        if getattr(_jdist.global_state, "client", None) is not None:
            return _jdist.global_state.process_id or 0
    except Exception:
        pass
    import jax
    try:
        return jax.process_index()
    except Exception:
        return 0


def num_workers() -> int:
    try:
        from jax._src import distributed as _jdist
        if getattr(_jdist.global_state, "client", None) is not None:
            return _jdist.global_state.num_processes or 1
    except Exception:
        pass
    import jax
    try:
        return jax.process_count()
    except Exception:
        return 1


def _comm():
    """One-device-per-process mesh for cross-process reductions.

    Prefers the default backend (a TPU slice spans all processes natively);
    falls back to the CPU backend, whose gloo collectives span hosts when
    ``initialize`` ran first.
    """
    global _COMM
    if _COMM is not None:
        return _COMM
    import numpy as np
    import jax
    from jax.sharding import Mesh

    n = num_workers()

    def pick(devs):
        by_proc = {}
        for d in devs:
            by_proc.setdefault(d.process_index, d)
        if len(by_proc) < n:
            return None
        return [by_proc[i] for i in range(n)]

    devs = pick(jax.devices())
    if devs is None:
        devs = pick(jax.devices("cpu"))
    if devs is None:
        raise RuntimeError(
            "no backend spans all %d processes — was dist.initialize() "
            "called before the first device access?" % n)
    mesh = Mesh(np.array(devs), ("proc",))
    local = devs[rank()]
    _COMM = (mesh, local)
    return _COMM


def _psum_fn(shape, dtype):
    key = ("psum", shape, str(dtype))
    fn = _FN_CACHE.get(key)
    if fn is None:
        import jax
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        mesh, _ = _comm()
        shard = partial(shard_map, mesh=mesh, in_specs=P("proc"),
                        out_specs=P())
        fn = jax.jit(shard(lambda s: jax.lax.psum(s[0], "proc")))
        _FN_CACHE[key] = fn
    return fn


def allreduce_sum(x):
    """Sum an identically-shaped per-process array across all processes;
    returns the reduction as a local jax array (replicated semantics)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    n = num_workers()
    if n == 1:
        return jnp.asarray(x)
    mesh, local = _comm()
    xl = jax.device_put(jnp.asarray(x), local)
    garr = jax.make_array_from_single_device_arrays(
        (n,) + xl.shape, NamedSharding(mesh, P("proc")), [xl[None]])
    out = _psum_fn(xl.shape, xl.dtype)(garr)
    return out.addressable_data(0)


def broadcast(x, root: int = 0):
    """Every process gets ``root``'s value (psum of one-hot contribution)."""
    import jax.numpy as jnp
    if num_workers() == 1:
        return jnp.asarray(x)
    contrib = jnp.asarray(x) if rank() == root else jnp.zeros_like(
        jnp.asarray(x))
    return allreduce_sum(contrib)


def barrier():
    """Block until every process reaches this point."""
    import jax
    if num_workers() == 1:
        return
    jax.block_until_ready(allreduce_sum(jax.numpy.zeros((1,))))


# ------------------------------------------------------- failure detection


def _client():
    import jax._src.distributed as _jdist
    return getattr(_jdist.global_state, "client", None)


_hb_started = False
_hb_stop = None           # threading.Event for the publisher thread
_hb_thread = None
# reader-side observations: rank -> (last counter, local time first seen)
_hb_seen = {}


def heartbeat_start(period: Optional[float] = None,
                    progress_fn: Optional[Callable[[], object]] = None,
                    as_rank: Optional[int] = None) -> bool:
    """Publish this worker's liveness to the coordinator's key-value store
    every ``period`` seconds (reference: ps-lite worker heartbeats to the
    scheduler, feeding kvstore.h:287 get_num_dead_node). The payload is a
    monotonically increasing beat COUNTER, not a wall-clock timestamp —
    staleness is judged on the reader's own clock, so cross-host clock
    skew cannot fake deaths. Idempotent; returns False when no
    coordination client exists (single process).

    ``period`` defaults to the ``MXNET_TPU_HEARTBEAT_PERIOD`` knob.

    With ``progress_fn``, the beat is PROGRESS-COUPLED: the counter only
    advances when ``progress_fn()`` returns a different token than the
    last tick — the hook for tying a worker's liveness to actual work
    progress (a file mtime, a step counter). A publisher that stops
    progressing stops advancing, and peers' :func:`num_dead_nodes`
    counts it dead once the staleness window passes. NB: couple with
    care in bulk-synchronous pods — one wedged member stalls EVERY
    member's progress, so progress-coupled beats there make the whole
    pod look dead at once (the pod coordinator publishes a plain beat
    for exactly this reason).

    ``as_rank`` names the heartbeat key explicitly (the pod coordinator
    publishes under its ORIGINAL pod rank across control-plane
    re-hostings); default is this process's coordination rank."""
    global _hb_started, _hb_stop, _hb_thread
    import logging
    import threading
    backend = _kv()
    if backend is None:
        return False
    if _hb_started:
        return True
    if period is None:
        from .. import config as _config
        period = float(_config.get("MXNET_TPU_HEARTBEAT_PERIOD"))
    _hb_started = True
    _hb_stop = threading.Event()

    me = "mxnet_hb/%d" % (rank() if as_rank is None else int(as_rank))
    stop = _hb_stop

    def beat():
        n = 0
        warned = False
        last_token = object()       # sentinel: first tick always beats
        while not stop.is_set():
            if progress_fn is None:
                n += 1
            else:
                try:
                    token = progress_fn()
                except Exception:                          # noqa: BLE001
                    token = last_token     # unreadable progress = stalled
                if token != last_token or n == 0:
                    last_token = token
                    n += 1
            try:
                # the captured backend, not kv_set: the fault harness's
                # dist.kv site must keep DETERMINISTIC arrival counts,
                # and a background beat firing it would wreck them
                backend.set(me, str(n))
                warned = False      # recovered: re-arm the warning
            except Exception as exc:
                # transient coordinator hiccups must not kill the beat —
                # a dead thread would report this live worker dead forever
                if not warned:
                    logging.warning("heartbeat publish failed "
                                    "(will keep retrying): %s", exc)
                    warned = True
            stop.wait(period)

    _hb_thread = threading.Thread(target=beat, daemon=True,
                                  name="mxnet-heartbeat")
    _hb_thread.start()
    return True


def heartbeat_stop(timeout: float = 2.0):
    """Stop the publisher thread (e.g. before a deliberate clean exit, so
    peers' ``get_num_dead_node`` sees this worker as *gone* rather than
    freshly-beating). Idempotent."""
    global _hb_started, _hb_stop, _hb_thread
    if _hb_stop is not None:
        _hb_stop.set()
    if _hb_thread is not None:
        _hb_thread.join(timeout)
    _hb_started, _hb_stop, _hb_thread = False, None, None


def dead_ranks(stale_after: float = 20.0, timeout_ms: int = 1000,
               ranks: Optional[Iterable[int]] = None) -> List[int]:
    """Ranks whose heartbeat is missing, or whose beat counter has not
    advanced for ``stale_after`` seconds of the CALLER's clock (two
    observations are needed to declare staleness, so a first call never
    false-positives on a slow-but-alive worker). The pod coordinator
    keys membership decisions on this list; :func:`num_dead_nodes` is
    its count.

    ``ranks`` names the heartbeat keys to check (the pod coordinator
    passes its CURRENT membership's original ranks); default is every
    coordination rank of this process's world.

    The liveness math reads ``time.monotonic()`` ONLY — an NTP step on
    either host must never expire a deadline or resurrect a corpse (the
    ``wall-clock`` lint rule is wired over this module)."""
    import time
    backend = _kv()
    if backend is None:
        return []
    if ranks is None:
        ranks = range(num_workers())
    dead: List[int] = []
    now = time.monotonic()
    for r in ranks:
        try:
            counter = int(backend.get("mxnet_hb/%d" % r, timeout_ms))
        except (TypeError, ValueError):
            dead.append(r)          # never heartbeated within the timeout
            continue
        except Exception:                                  # noqa: BLE001
            dead.append(r)          # backend unreachable: unreadable rank
            continue
        prev = _hb_seen.get(r)
        if prev is None or prev[0] != counter:
            _hb_seen[r] = (counter, now)
        elif now - prev[1] > stale_after:
            dead.append(r)
    return dead


def num_dead_nodes(stale_after: float = 20.0, timeout_ms: int = 1000) -> int:
    """Count of :func:`dead_ranks` (reference: kvstore.h:287
    get_num_dead_node over ps-lite's scheduler heartbeat table)."""
    return len(dead_ranks(stale_after=stale_after, timeout_ms=timeout_ms))


def reset_liveness() -> None:
    """Forget reader-side heartbeat observations (tests, and a monitor
    re-arming after a pod generation change: stale observations of a
    previous generation must not instantly re-declare a rejoined rank
    dead)."""
    _hb_seen.clear()


# --------------------------------------------------- coordination KV store
#
# Two backends serve the same kv_set/kv_get surface:
#
# * the jax.distributed coordination client (training children — the
#   data plane: the checkpoint commit barrier rides it), and
# * a :class:`PodKVClient` installed via :func:`set_kv_backend` (the pod
#   coordinators — the control plane). The control plane CANNOT ride
#   jax's client: its error-polling thread LOG(FATAL)s the whole process
#   the moment the coordination service dies (xla client.h
#   missed_heartbeat_callback; the Python override crashes with
#   std::bad_cast on this jaxlib) — the exact event leader fail-over
#   exists to survive. A coordinator losing its KV server must ADJUDICATE
#   (probe ring), not die.

_KV_BACKEND = None          # PodKVClient installed by the pod coordinator


class _JaxKV(object):
    """Adapter presenting the jax coordination client as a KV backend."""

    def __init__(self, client):
        self._client = client

    def set(self, key: str, value: str) -> None:
        try:
            self._client.key_value_set(key, value, allow_overwrite=True)
        except TypeError:           # older jaxlib: no overwrite kwarg
            try:
                self._client.key_value_delete(key)
            except Exception:                              # noqa: BLE001
                pass
            self._client.key_value_set(key, value)

    def get(self, key: str, timeout_ms: int) -> Optional[str]:
        try:
            v = self._client.blocking_key_value_get(key, int(timeout_ms))
        except Exception:                                  # noqa: BLE001
            return None
        return v.decode() if isinstance(v, bytes) else v


def set_kv_backend(backend) -> None:
    """Install (or with ``None`` remove) an explicit KV backend that
    :func:`kv_set`/:func:`kv_get`/:func:`heartbeat_start`/
    :func:`dead_ranks` use INSTEAD of the jax coordination client. The
    pod coordinator points this at its :class:`PodKVClient`; re-pointing
    it at a re-hosted server is the whole of a control-plane migration."""
    global _KV_BACKEND
    _KV_BACKEND = backend


def kv_backend_active() -> bool:
    return _KV_BACKEND is not None or _client() is not None


def _kv():
    if _KV_BACKEND is not None:
        return _KV_BACKEND
    client = _client()
    return _JaxKV(client) if client is not None else None


def _kv_retries() -> int:
    from .. import config as _config
    return max(0, int(_config.get("MXNET_TPU_KV_RETRIES")))


def kv_set(key: str, value: str) -> None:
    """Publish to the coordination key-value store (overwrite allowed),
    retrying KV flakes (``MXNET_TPU_KV_RETRIES`` bounded attempts, each
    counted ``dist_kv_retry``) before the error propagates. Raises
    RuntimeError when no backend exists. Fault site: ``dist.kv``."""
    import time
    from .. import faults as _faults
    backend = _kv()
    if backend is None:
        raise RuntimeError("kv_set(%r): no coordination KV backend — was "
                           "dist.initialize() called?" % key)
    retries = _kv_retries()
    for attempt in range(retries + 1):
        try:
            if _faults.ARMED:
                _faults.fire("dist.kv", default_kind="raise")
            backend.set(key, value)
            return
        except Exception:                                  # noqa: BLE001
            if attempt >= retries:
                raise
            from .. import profiler as _profiler
            _profiler.incr_counter("dist_kv_retry")
            time.sleep(0.05 * (2 ** attempt))


def kv_get(key: str, timeout_ms: int) -> Optional[str]:
    """Blocking get with a bounded deadline; None on timeout (the caller
    decides whether an absent key is an error — the checkpoint commit
    barrier and the pod rendezvous both do, naming the absent rank).
    Injected KV flakes (fault site ``dist.kv``) are retried with the
    same bounded budget as :func:`kv_set`; an absent key is NOT a flake
    and returns None immediately."""
    import time
    from .. import faults as _faults
    backend = _kv()
    if backend is None:
        raise RuntimeError("kv_get(%r): no coordination KV backend — was "
                           "dist.initialize() called?" % key)
    retries = _kv_retries()
    for attempt in range(retries + 1):
        try:
            if _faults.ARMED:
                _faults.fire("dist.kv", default_kind="raise")
            return backend.get(key, int(timeout_ms))
        except Exception:                                  # noqa: BLE001
            if attempt >= retries:
                raise
            from .. import profiler as _profiler
            _profiler.incr_counter("dist_kv_retry")
            time.sleep(0.05 * (2 ** attempt))


# ----------------------------------------- re-hostable pod control plane
#
# Reference: the ps-lite scheduler is its own tiny process, not a
# training worker — and so is this. A line-based TCP KV service the pod
# coordinators use for rendezvous, heartbeats, restart requests and the
# done barrier. The LEADER (lowest live rank) hosts it; when the
# leader's host dies, the successor re-hosts it on its published
# fail-over port and every survivor re-points its client — no process
# ever has to survive a jax coordination-service death (see the backend
# note above).
#
# Protocol (one UTF-8 line per request/reply; values base64 so any JSON
# payload stays line-safe):
#
#   SET <key> <b64>          -> OK
#   GET <key> <timeout_ms>   -> VAL <b64> | NONE   (server-side blocking
#                               wait for the key, bounded by timeout_ms)
#   PING                     -> PONG
#   CLOCK                    -> CLK <wall_seconds>  (the flight-recorder
#                               clock exchange: NTP-style offset
#                               estimation against the leader's clock)

_KV_MAGIC_PING = b"PING\n"
_KV_MAGIC_PONG = b"PONG\n"


def _b64e(value: str) -> str:
    import base64
    return base64.b64encode(value.encode("utf-8")).decode("ascii")


def _b64d(value: str) -> str:
    import base64
    return base64.b64decode(value.encode("ascii")).decode("utf-8")


class PodKVServer(object):
    """The control-plane KV service (one per pod, on the current
    leader's host). ``stop()`` is abrupt by design — the ``coordsvc``
    fault kind drills exactly this shape (service dead, host alive)."""

    def __init__(self, port: int = 0, host: str = ""):
        import socket
        import threading
        from .. import lockcheck as _lockcheck
        self._store: Dict[str, str] = {}
        self._cond = _lockcheck.Condition(name="dist.podkv_cond")
        self._stopped = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="mxpod-kv-server",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Close the listener and wake every blocked GET. Idempotent."""
        import socket
        with self._cond:
            if self._stopped:
                return
            self._stopped = True
            self._cond.notify_all()
        try:
            # shutdown BEFORE close: close() alone leaves a concurrently
            # accept()-blocked listener alive in the kernel, silently
            # serving new connections until the next accept returns
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass

    # ------------------------------------------------------------ server
    def _accept_loop(self) -> None:
        import socket
        import threading
        while True:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return              # stop() closed the listener
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn) -> None:
        import time
        try:
            conn.settimeout(300.0)
            rfile = conn.makefile("r", encoding="utf-8", newline="\n")
            for line in rfile:
                parts = line.strip().split(" ")
                if not parts or not parts[0]:
                    continue
                op = parts[0]
                if op == "PING":
                    conn.sendall(_KV_MAGIC_PONG)
                elif op == "CLOCK":
                    # WALL clock on purpose: the reply is compared
                    # against the CALLER's wall clock to estimate the
                    # cross-host offset the blackbox merger aligns on
                    # (monotonic clocks have per-boot arbitrary zeros)
                    conn.sendall(("CLK %r\n"
                                  % time.time()).encode("ascii"))  # mx-lint: allow(wall-clock)
                elif op == "SET" and len(parts) == 3:
                    with self._cond:
                        self._store[parts[1]] = parts[2]
                        self._cond.notify_all()
                    conn.sendall(b"OK\n")
                elif op == "GET" and len(parts) == 3:
                    deadline = time.monotonic() + int(parts[2]) / 1000.0
                    with self._cond:
                        while parts[1] not in self._store \
                                and not self._stopped:
                            left = deadline - time.monotonic()
                            if left <= 0:
                                break
                            self._cond.wait(min(left, 1.0))
                        val = self._store.get(parts[1])
                    conn.sendall(("VAL %s\n" % val).encode("ascii")
                                 if val is not None else b"NONE\n")
                else:
                    conn.sendall(b"ERR\n")
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class PodKVClient(object):
    """One-request-per-connection client of :class:`PodKVServer`.

    Connection failures are judged FAST (a dead server must read as dead
    within one quick retry, not a full blocking window) — bootstrap
    patience lives in :meth:`ping`, which retries connecting until its
    deadline (the follower-waits-for-the-leader's-server window)."""

    def __init__(self, address: str, connect_timeout: Optional[float]
                 = None):
        host, _, port = address.rpartition(":")
        self.address = address
        self._host = host or "127.0.0.1"
        self._port = int(port)
        if connect_timeout is None:
            from .. import config as _config
            connect_timeout = float(_config.get("MXNET_TPU_PROBE_TIMEOUT"))
        self._connect_timeout = float(connect_timeout)

    def _request(self, line: str, read_timeout: float) -> Optional[str]:
        import socket
        import time
        reply = None
        for attempt in range(2):        # one quick re-dial, then give up
            try:
                conn = socket.create_connection(
                    (self._host, self._port),
                    timeout=self._connect_timeout)
            except OSError:
                time.sleep(0.05)
                continue
            try:
                conn.settimeout(read_timeout)
                conn.sendall(line.encode("utf-8"))
                reply = conn.makefile(
                    "r", encoding="utf-8", newline="\n").readline().strip()
            except OSError:
                reply = None
            finally:
                try:
                    conn.close()
                except OSError:
                    pass
            if reply:
                return reply
        return None

    def ping(self, deadline_s: float) -> bool:
        """Bounded wait for the server to answer (bootstrap: the leader
        may not have bound its port yet)."""
        import time
        t_end = time.monotonic() + max(0.0, deadline_s)
        while True:
            if self._request("PING\n", read_timeout=2.0) == "PONG":
                return True
            if time.monotonic() >= t_end:
                return False
            time.sleep(0.2)

    def set(self, key: str, value: str) -> None:
        reply = self._request("SET %s %s\n" % (key, _b64e(value)),
                              read_timeout=10.0)
        if reply != "OK":
            raise OSError("pod KV server %s unreachable for SET %s"
                          % (self.address, key))

    def get(self, key: str, timeout_ms: int) -> Optional[str]:
        reply = self._request(
            "GET %s %d\n" % (key, int(timeout_ms)),
            read_timeout=int(timeout_ms) / 1000.0 + 10.0)
        if reply is None or reply == "NONE":
            return None
        if reply.startswith("VAL "):
            return _b64d(reply[4:])
        return None

    def clock_offset(self, samples: int = 5) -> Optional[float]:
        """NTP-style estimate of ``local_wall - server_wall``: each
        sample brackets a CLOCK request between two local wall reads
        and assumes the server stamped at the midpoint; the minimum-RTT
        sample wins (its midpoint assumption has the tightest error
        bound — half its RTT). None when the server never answered.

        Wall clocks on BOTH ends on purpose — the whole point is to
        compare wall clocks across hosts so the flight-recorder merger
        can align per-host timelines; the RTT bound makes the jumpiness
        of wall time measurable instead of hidden."""
        import time
        best = None
        for _ in range(max(1, int(samples))):
            t0 = time.time()     # mx-lint: allow(wall-clock)
            reply = self._request("CLOCK\n", read_timeout=2.0)
            t1 = time.time()     # mx-lint: allow(wall-clock)
            if not reply or not reply.startswith("CLK "):
                continue
            try:
                server = float(reply[4:])
            except ValueError:
                continue
            rtt = t1 - t0
            offset = (t0 + t1) / 2.0 - server
            if best is None or rtt < best[0]:
                best = (rtt, offset)
        return None if best is None else best[1]


# ------------------------------------------------- peer liveness probes

_PROBE_Q = b"mxpr?\n"
_PROBE_A = b"mxpr!\n"


def _recv_exact(conn, n: int) -> bytes:
    """Read up to ``n`` bytes, looping past short reads; returns what
    arrived before EOF/timeout. TCP is a byte stream — a single recv()
    can short-read a split handshake, and a short-read misjudging a
    LIVE peer as confirmed-dead shrinks the fail-over electorate toward
    split-brain, so the caller classifies on the COMPLETE prefix."""
    buf = b""
    while len(buf) < n:
        try:
            chunk = conn.recv(n - len(buf))
        except OSError:
            break
        if not chunk:
            break
        buf += chunk
    return buf


class ProbeRing(object):
    """Peer-to-peer TCP liveness listener, INDEPENDENT of the
    coordination service: every coordinator runs one and publishes its
    port in the generation's membership record, so when the KV control
    plane goes dark the survivors can still tell "the leader's host
    died" apart from "I am partitioned" — and a healthy majority
    recovers in place instead of draining for a job restart."""

    def __init__(self, port: int = 0):
        import socket
        import threading
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("", port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._thread = threading.Thread(target=self._serve,
                                        name="mxpod-probe-ring",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        import socket
        try:
            self._srv.shutdown(socket.SHUT_RDWR)   # wake a blocked accept
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass

    def _serve(self) -> None:
        while True:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            try:
                conn.settimeout(2.0)
                if _recv_exact(conn, len(_PROBE_Q)) == _PROBE_Q:
                    conn.sendall(_PROBE_A)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass


def probe_peer(address: Optional[str],
               timeout: Optional[float] = None) -> str:
    """One liveness probe: ``"live"`` (the peer's probe ring answered),
    ``"dead"`` (its host's TCP stack POSITIVELY refused — the
    coordinator process is gone but the machine answers, e.g. SIGKILL),
    or ``"unreachable"`` (timeout / no route: a dead machine and a
    network partition look identical, so the caller must treat it as
    AMBIGUOUS — the majority arithmetic in the pod coordinator counts
    live vs. everything-not-positively-dead)."""
    import socket
    if not address or address.rpartition(":")[2] in ("", "0"):
        return "unreachable"
    if timeout is None:
        from .. import config as _config
        timeout = float(_config.get("MXNET_TPU_PROBE_TIMEOUT"))
    host, _, port = address.rpartition(":")
    try:
        conn = socket.create_connection((host or "127.0.0.1", int(port)),
                                        timeout=timeout)
    except ConnectionRefusedError:
        return "dead"
    except OSError:
        return "unreachable"
    try:
        conn.settimeout(timeout)
        conn.sendall(_PROBE_Q)
        reply = _recv_exact(conn, len(_PROBE_A))
    except OSError:
        return "unreachable"
    finally:
        try:
            conn.close()
        except OSError:
            pass
    if reply == _PROBE_A:
        return "live"
    if reply and not _PROBE_A.startswith(reply):
        # a recycled port ACTIVELY speaking another protocol is NOT our
        # coordinator: positively dead
        return "dead"
    # silence or a partial prefix (slow peer, split segment): ambiguous —
    # never confirmed-dead on an incomplete handshake
    return "unreachable"


def elect_leader(live: Iterable[int]) -> int:
    """The deterministic election: lowest live rank. Every survivor
    computes it from the SAME generation record + probe results, so no
    communication is needed to agree (and none is available — the
    election runs exactly when the control plane is dark)."""
    return min(live)

"""Device-mesh utilities — the TPU-native distribution substrate.

Reference translation (SURVEY.md §2.21): the reference's
DataParallelExecutorGroup (python/mxnet/module/executor_group.py:99) manually
slices batches across a ctx list and KVStore Comm (src/kvstore/comm.h) sums
gradients device-by-device. On TPU the same capabilities are sharding
annotations on ONE jitted program over a ``jax.sharding.Mesh``: the batch is
sharded over the ``data`` axis, parameters are replicated (or sharded over
``model`` for tensor parallelism), and XLA inserts the psum/all-gather
collectives over ICI.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, \
    Union

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..context import Context

__all__ = ["make_mesh", "data_parallel_mesh", "batch_sharding",
           "replicated_sharding", "shard_batch", "replicate", "P", "Mesh",
           "NamedSharding", "mesh_devices", "sharding_island",
           "axis_sizes", "validate_spec", "resolve_layout_spec",
           "host_partition"]

# a layout maps array name -> PartitionSpec: a dict (exact name match
# wins, then regex fullmatch), a callable name -> spec, a SpecLayout
# (layout.py — overrides + name heuristic, shape-aware), or None
# (everything fully replicated)
Layout = Union[None, Dict[str, Any], Callable[[str], Any]]


def resolve_layout_spec(layout: Layout, name: str, shape=None, dtype=None):
    """Resolve one array's partition spec from a layout — THE canonical
    name->spec resolution, shared by ``Module(param_shardings=...)``
    bind-time placement and checkpoint reshard-on-load (two copies of
    this precedence once drifted in the PR 8 spec-conflict audit; keep
    it single-sourced). ``None`` = replicated.

    A :class:`~mxnet_tpu.parallel.layout.SpecLayout` resolves through
    its own ``spec_for`` (overrides first, then the name heuristic) with
    the array's ``shape`` so divisibility-unsafe specs are never
    emitted; checkpoint keys (``arg:``/``aux:``/``opt:`` prefixes) are
    stripped to the parameter name so optimizer-state leaves follow
    their parameter's spec."""
    if layout is None:
        return None
    if hasattr(layout, "spec_for"):               # SpecLayout (duck-typed)
        lookup = name
        if ":" in name:
            from .layout import strip_ckpt_key
            lookup = strip_ckpt_key(name)
            if lookup is None:                    # rng:/upd: bookkeeping
                return None
        return layout.spec_for(lookup, shape=shape, dtype=dtype)
    if callable(layout):
        return layout(name)
    spec = layout.get(name)
    if spec is None:
        for pat, s in layout.items():
            if re.fullmatch(pat, name):
                return s
    return spec


def sharding_island():
    """This module's canonical layout claims, auditable by
    ``analysis.sharding_passes.check_islands`` — drawn from the unified
    SpecLayout (layout.py) like every other island, so the audit reports
    zero cross-island disagreements (ROADMAP item 1, done)."""
    from .layout import island_specs
    return "mesh", island_specs("mesh")


def mesh_devices(contexts: Optional[Sequence[Context]] = None) -> List[jax.Device]:
    if contexts is not None:
        return [c.jax_device for c in contexts]
    import os
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        # explicit CPU request (the virtual-mesh test rig, SURVEY.md §4) —
        # some accelerator plugins register even when JAX_PLATFORMS says cpu
        return list(jax.devices("cpu"))
    return list(jax.devices())


def make_mesh(shape: Optional[Dict[str, int]] = None,
              contexts: Optional[Sequence[Context]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh.

    ``shape`` maps axis name -> size, e.g. ``{"data": 4, "model": 2}``; a
    size of -1 absorbs the remaining devices. Defaults to one ``data`` axis
    over all visible devices.
    """
    devs = list(devices) if devices is not None else mesh_devices(contexts)
    if shape is None:
        shape = {"data": len(devs)}
    names = list(shape.keys())
    sizes = list(shape.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devs) // known
    total = int(np.prod(sizes))
    if total > len(devs):
        # fall back to the host's virtual CPU devices — the TPU twin of the
        # reference running multi-device suites on cpu(0)/cpu(1)
        # (tests/python/unittest/test_multi_device_exec.py, SURVEY.md §4)
        cpus = list(jax.devices("cpu"))
        if devices is None and contexts is None and total <= len(cpus):
            devs = cpus
        else:
            raise ValueError("mesh needs %d devices, only %d visible"
                             % (total, len(devs)))
    arr = np.array(devs[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def axis_sizes(mesh: Mesh) -> Dict[str, int]:
    """Axis name -> size of a named mesh."""
    return {str(a): int(s)
            for a, s in zip(mesh.axis_names, mesh.devices.shape)}


def validate_spec(mesh: Mesh, spec, shape: Tuple[int, ...],
                  name: str = "<array>") -> None:
    """Reject a PartitionSpec that cannot lay ``shape`` out on ``mesh``:
    unknown axis names, or a sharded dimension the axis sizes do not
    divide. The error NAMES the offending array — elastic reshard-on-load
    and ``Module`` param placement both route here so an N-chip
    checkpoint restored onto an incompatible M-chip mesh fails with the
    array and dimension spelled out, not a shape error deep inside XLA.
    """
    sizes = axis_sizes(mesh)
    parts = tuple(spec) if spec is not None else ()
    if len(parts) > len(shape):
        raise ValueError(
            "%s: partition spec %s has rank %d but array has rank %d"
            % (name, parts, len(parts), len(shape)))
    for dim, part in enumerate(parts):
        if part is None:
            continue
        axes = part if isinstance(part, tuple) else (part,)
        k = 1
        for a in axes:
            if a not in sizes:
                raise ValueError(
                    "%s: partition spec names axis %r but mesh %r has "
                    "axes %s" % (name, a, dict(sizes), sorted(sizes)))
            k *= sizes[a]
        if shape[dim] % k:
            raise ValueError(
                "%s: dimension %d of shape %s is not divisible by the "
                "%d-way sharding over axes %r (mesh %r)"
                % (name, dim, tuple(shape), k, axes, dict(sizes)))


def data_parallel_mesh(contexts: Sequence[Context]) -> Mesh:
    """Mesh with a single ``data`` axis over a ctx list — the TPU twin of
    Module(context=[...]) data parallelism."""
    return make_mesh({"data": len(contexts)}, contexts=contexts)


def batch_sharding(mesh: Mesh, axis: str = "data", batch_dim: int = 0):
    spec = [None] * (batch_dim + 1)
    spec[batch_dim] = axis
    return NamedSharding(mesh, P(*spec))


def replicated_sharding(mesh: Mesh):
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, value, axis: str = "data", batch_dim: int = 0):
    """Place an array batch-sharded over the mesh."""
    return jax.device_put(value, batch_sharding(mesh, axis, batch_dim))


def replicate(mesh: Mesh, value):
    """Place an array fully replicated over the mesh."""
    return jax.device_put(value, replicated_sharding(mesh))


def host_partition(mesh: Optional[Mesh] = None) -> Tuple[int, int]:
    """``(host_rank, host_world)`` for data-plane shard ownership — who
    feeds which slice of the global batch stream (``mx.data.DataLoader
    (part="auto")``).

    Resolution order:

    1. an explicit ``mesh``: its devices' PROCESS set — each host loads
       only the stream slice its addressable devices consume when the
       batch is ``device_put`` onto the ``data`` axis (a single-process
       mesh, however many devices, is one host: device count never
       enters the partition);
    2. the active ``jax.distributed`` pod (state probe only — never
       initializes anything, mirroring ``checkpoint.format.pod_info``);
    3. the DMLC launcher env (``DMLC_WORKER_ID``/``DMLC_NUM_WORKER`` —
       coordinated pods whose children predate jax.distributed init);
    4. ``(0, 1)`` — single host.
    """
    if mesh is not None:
        try:
            procs = sorted({d.process_index
                            for d in np.asarray(mesh.devices).flat})
            if len(procs) > 1:
                me = jax.process_index()
                return (procs.index(me) if me in procs else 0,
                        len(procs))
        except Exception:                              # noqa: BLE001
            pass
    import sys
    if "jax" in sys.modules:
        try:
            from jax._src import distributed as _jdist
            state = _jdist.global_state
            if getattr(state, "client", None) is not None:
                return (int(state.process_id or 0),
                        int(state.num_processes or 1))
        except Exception:                              # noqa: BLE001
            pass
    import os
    try:
        world = int(os.environ.get("DMLC_NUM_WORKER", "1") or 1)
        rank = int(os.environ.get("DMLC_WORKER_ID", "0") or 0)
    except ValueError:
        return 0, 1
    if world > 1:
        return min(rank, world - 1), world
    return 0, 1

"""Ring attention — sequence/context parallelism over the device mesh.

The reference predates long-context training (SURVEY.md §5.7: its sequence
story is BucketingModule + fused RNN); the task spec requires the modern TPU
capability: shard the sequence axis across devices and compute exact
attention by rotating key/value blocks around the ring with ``ppermute``
while accumulating an online softmax (blockwise attention), so no device
ever materializes the full S×S score matrix. Collectives ride ICI
neighbor-to-neighbor, overlapping with the per-block matmuls (the pattern
from the ring-attention literature; see PAPERS.md).
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

__all__ = ["ring_attention", "ring_self_attention",
           "local_attention_block", "chunked_causal_attention",
           "sharding_island"]


def sharding_island():
    """Canonical layout claims of the sequence-parallel island (audited
    by ``analysis.sharding_passes.check_islands``): drawn from the
    unified SpecLayout — the sequence dim rides the canonical ``tp``
    model axis and the batch layout matches every other island, so the
    audit reports zero cross-island disagreements."""
    from .layout import island_specs
    return "ring_attention", island_specs("ring_attention")


def local_attention_block(q, k, v, mask=None, scale=None):
    """One (q-block, kv-block) attention contribution with running-softmax
    statistics. Returns (o_unnormalized, row_sum l, row_max m)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isneginf(m)[..., None], 0.0, p)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype))
    return o, l, m


def chunked_causal_attention(q, k, v, scale=None, chunk: int = 512):
    """Single-device blockwise causal attention — the serving prefill's
    long-context path. Same online-softmax accumulation the ring kernel
    rotates across devices, applied to local sequence chunks so no
    (S, S) score matrix ever materializes: for prefill buckets past the
    chunk size the score working set drops from O(S^2) to
    O(S * chunk). Strictly-future (q-chunk, kv-chunk) pairs are skipped
    at trace time (the causal half of the schedule), so the chunk grid
    is lower-triangular like the ring's causal mask.

    q, k, v: (B, H, S, D); returns (B, H, S, D) in q's dtype.
    """
    b, h, s, d = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if s <= chunk:
        mask = (jnp.arange(s)[:, None] >= jnp.arange(s)[None, :])[None, None]
        o, l, m = local_attention_block(q, k, v, mask, scale)
        return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
    if s % chunk:
        raise ValueError("sequence %d is not a multiple of chunk %d "
                         "(prefill buckets are pow2 — pick a pow2 chunk)"
                         % (s, chunk))
    n = s // chunk
    outs = []
    for qi in range(n):
        q_blk = lax.slice_in_dim(q, qi * chunk, (qi + 1) * chunk, axis=2)
        q_pos = qi * chunk + jnp.arange(chunk)
        o_acc = jnp.zeros((b, h, chunk, d), jnp.float32)
        l_acc = jnp.zeros((b, h, chunk), jnp.float32)
        m_acc = jnp.full((b, h, chunk), -jnp.inf, jnp.float32)
        for ki in range(qi + 1):          # causal: only past/diag chunks
            k_blk = lax.slice_in_dim(k, ki * chunk, (ki + 1) * chunk,
                                     axis=2)
            v_blk = lax.slice_in_dim(v, ki * chunk, (ki + 1) * chunk,
                                     axis=2)
            if ki == qi:                  # diagonal chunk needs the mask
                k_pos = ki * chunk + jnp.arange(chunk)
                mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            else:
                mask = None
            o_blk, l_blk, m_blk = local_attention_block(
                q_blk, k_blk, v_blk, mask, scale)
            m_new = jnp.maximum(m_acc, m_blk)
            m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
            alpha = jnp.exp(jnp.where(jnp.isneginf(m_acc), -jnp.inf,
                                      m_acc - m_safe))
            beta = jnp.exp(jnp.where(jnp.isneginf(m_blk), -jnp.inf,
                                     m_blk - m_safe))
            o_acc = o_acc * alpha[..., None] + o_blk * beta[..., None]
            l_acc = l_acc * alpha + l_blk * beta
            m_acc = m_new
        outs.append(o_acc / jnp.maximum(l_acc, 1e-30)[..., None])
    return jnp.concatenate(outs, axis=2).astype(q.dtype)


def _ring_attention_shard(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float]):
    """Per-shard body: q/k/v are the local sequence blocks
    (B, H, S_local, D); rotate k/v around the ring, accumulate online
    softmax."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    if scale is None:
        scale = 1.0 / np.sqrt(d)

    o_acc = jnp.zeros((b, h, s_q, d), jnp.float32)
    l_acc = jnp.zeros((b, h, s_q), jnp.float32)
    m_acc = jnp.full((b, h, s_q), -jnp.inf, jnp.float32)
    if hasattr(lax, "pvary"):
        # mark initial carries as varying over the ring axis so the scan
        # carry types match (shard_map vma typing in recent jax)
        o_acc, l_acc, m_acc = lax.pvary((o_acc, l_acc, m_acc), (axis_name,))

    q_pos = my_idx * s_q + jnp.arange(s_q)

    def body(i, carry):
        o_acc, l_acc, m_acc, k_cur, v_cur = carry
        kv_idx = (my_idx - i) % axis_size  # owner of the block we now hold
        if causal:
            k_pos = kv_idx * s_k + jnp.arange(s_k)
            mask = q_pos[:, None] >= k_pos[None, :]
            mask = mask[None, None]
        else:
            mask = None
        o_blk, l_blk, m_blk = local_attention_block(q, k_cur, v_cur, mask,
                                                    scale)
        # online softmax merge
        m_new = jnp.maximum(m_acc, m_blk)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.exp(jnp.where(jnp.isneginf(m_acc), -jnp.inf,
                                  m_acc - m_safe))
        beta = jnp.exp(jnp.where(jnp.isneginf(m_blk), -jnp.inf,
                                 m_blk - m_safe))
        o_new = o_acc * alpha[..., None] + o_blk * beta[..., None]
        l_new = l_acc * alpha + l_blk * beta
        # rotate kv to the next device (neighbor exchange on ICI)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o_new, l_new, m_new, k_nxt, v_nxt

    o_acc, l_acc, m_acc, _, _ = lax.fori_loop(
        0, axis_size, body, (o_acc, l_acc, m_acc, k, v))
    out = o_acc / jnp.maximum(l_acc, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name: Optional[str] = None,
                   causal: bool = False, scale: Optional[float] = None):
    """Exact attention with the sequence axis sharded over ``axis_name``.

    q, k, v: (B, H, S, D) arrays (global view); S is sharded over the mesh
    axis. Returns (B, H, S, D) with the same sharding. ``axis_name=None``
    resolves to the legacy ``sp`` axis when the mesh carries it, else
    the unified SpecLayout's model axis (``tp``).
    """
    if axis_name is None:
        from .layout import resolve_model_axis
        axis_name = resolve_model_axis(mesh, "sp")
    elif axis_name not in mesh.axis_names:
        raise ValueError("mesh has no axis %r (axes: %s)"
                         % (axis_name, tuple(mesh.axis_names)))
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(_ring_attention_shard, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ring_self_attention(x, w_qkv, w_out, mesh: Mesh, num_heads: int,
                        axis_name: Optional[str] = None,
                        causal: bool = False):
    """Full self-attention layer with sequence-parallel ring attention:
    x (B, S, E) sharded on S; projections are local (no collective), only
    the kv ring moves data."""
    b, s, e = x.shape
    d = e // num_heads
    qkv = jnp.einsum("bse,ecf->bscf", x,
                     w_qkv.reshape(e, 3, e)).reshape(b, s, 3, num_heads, d)
    q = qkv[:, :, 0].transpose(0, 2, 1, 3)
    k = qkv[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, 2].transpose(0, 2, 1, 3)
    o = ring_attention(q, k, v, mesh, axis_name=axis_name, causal=causal)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
    return jnp.einsum("bse,ef->bsf", o, w_out)

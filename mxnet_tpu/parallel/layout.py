"""SpecLayout — ONE named-axis layout (``data x fsdp x tp``) for the
whole stack (ROADMAP item 1, docs/architecture/parallelism.md).

Until this module, every parallel mode was its own *sharding island*:
``mesh.py`` assumed a ``data`` axis with replicated params, ``moe.py`` an
``expert`` axis, ``pipeline.py`` a ``pipe`` axis, ``ring_attention.py``
an ``sp`` axis — and the PR 8 ``check_islands`` audit kept the
disagreements (batch-layout split, axes the bound mesh does not carry)
visible in every run. A multi-chip job composed of two modes would pay a
resharding all-to-all at every island boundary, or worse, trace-fail on
a missing axis.

``SpecLayout`` is the unification (the SNIPPETS.md [1]-[3] blueprint):

* **One mesh**: ``data x fsdp x tp`` — always all three axes (a size-1
  axis costs nothing and keeps every PartitionSpec valid on every mesh
  shape, so "pure dp" is just ``data=8, fsdp=1, tp=1``).
* **One batch layout**: inputs shard over ``(data, fsdp)`` — both axes
  are data-parallel for activations; ``fsdp`` additionally shards
  parameters and optimizer states (ZeRO-style).
* **One model axis**: ``tp`` serves tensor parallelism AND the
  expert / pipeline-stage / sequence dimensions of the moe / pipeline /
  ring-attention islands — the same axis name everywhere, so no logical
  array is ever declared with two layouts.
* **One resolver**: :meth:`SpecLayout.spec_for` (explicit overrides
  first, then :func:`parameter_spec_from_name`'s name heuristic) is
  consumed by ``Module`` bind-time placement and checkpoint
  reshard-on-load through the same ``parallel.mesh.resolve_layout_spec``
  funnel, so a checkpoint restored by layout can never resolve
  differently than the bind that consumes it.

GSPMD does the rest: parameters sharded over ``fsdp`` are all-gathered
on use and their gradients reduce-scattered; the per-device resident
bytes of params + optimizer state drop to ``~1/fsdp`` of replicated
(``tools/perf/multichip_bench.py`` proves it against the analyzer's
``fsdp-opportunity`` numbers).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

__all__ = ["SpecLayout", "parameter_spec_from_name", "island_specs",
           "resolve_model_axis", "TP_COL_RULES", "TP_ROW_RULES"]

# ---------------------------------------------------------------- name rules
#
# The tensor-parallel name heuristic (docs/architecture/parallelism.md
# carries the full table). mxnet FullyConnected weights are (out, in):
# column-parallel = shard the OUT dim (dim 0), row-parallel = shard the
# IN dim (dim 1) — the Megatron pairing keeps the activation collective
# count at one all-reduce per block. Substring match on the lowercased
# parameter name; first hit wins, column rules before row rules.
TP_COL_RULES: Tuple[str, ...] = (
    "qkv", "q_proj", "k_proj", "v_proj", "query", "key_proj", "value",
    "fc1", "ffn_up", "up_proj", "gate", "wi", "inter", "embed",
)
TP_ROW_RULES: Tuple[str, ...] = (
    "out_proj", "o_proj", "fc2", "ffn_down", "down_proj", "wo", "attn_out",
)


def _divides(dim: int, k: int) -> bool:
    return k > 0 and dim % k == 0


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical ``data x fsdp x tp`` layout: axis names + sizes + the
    parameter-spec policy.

    ``data`` may be ``-1`` (absorb the remaining devices at mesh build);
    ``fsdp``/``tp`` must be concrete — the spec heuristic needs their
    sizes for divisibility, and a spec that does not divide is never
    emitted (the array stays replicated on that axis instead).

    ``overrides`` maps parameter names (exact, then regex fullmatch —
    the ``resolve_layout_spec`` precedence) to explicit PartitionSpecs;
    they win over the name heuristic. ``min_shard_bytes`` keeps small
    parameters replicated (an all-gather's latency beats the HBM savings
    below ~1 MiB — the same threshold as the analyzer's
    ``fsdp-opportunity`` pass).
    """

    data: int = -1
    fsdp: int = 1
    tp: int = 1
    data_axis: str = "data"
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"
    min_shard_bytes: int = 1 << 20
    overrides: Optional[Mapping[str, Any]] = None

    def __post_init__(self):
        for name, size in (("fsdp", self.fsdp), ("tp", self.tp)):
            if int(size) < 1:
                raise ValueError(
                    "SpecLayout.%s must be a concrete size >= 1 (got %r); "
                    "only data may be -1 (absorb)" % (name, size))
        if self.data == 0 or self.data < -1:
            raise ValueError("SpecLayout.data must be >= 1 or -1 (absorb), "
                             "got %r" % (self.data,))

    # ------------------------------------------------------------- mesh
    def axes(self) -> Dict[str, int]:
        """Axis name -> size, in canonical order (``make_mesh`` input)."""
        return {self.data_axis: int(self.data),
                self.fsdp_axis: int(self.fsdp),
                self.tp_axis: int(self.tp)}

    def sized(self, n_devices: int) -> "SpecLayout":
        """Resolve ``data=-1`` against a device count."""
        if self.data != -1:
            return self
        rest = int(self.fsdp) * int(self.tp)
        if n_devices % rest:
            raise ValueError(
                "layout fsdp*tp=%d does not divide %d devices"
                % (rest, n_devices))
        return dataclasses.replace(self, data=n_devices // rest)

    def world_size(self) -> Optional[int]:
        """Total devices, when fully sized (None while data=-1)."""
        if self.data == -1:
            return None
        return int(self.data) * int(self.fsdp) * int(self.tp)

    def mesh(self, contexts=None, devices=None):
        """Build the canonical ``data x fsdp x tp`` jax Mesh."""
        from .mesh import make_mesh
        return make_mesh(self.axes(), contexts=contexts, devices=devices)

    # ------------------------------------------------------------- specs
    def batch_spec(self):
        """Activations/batches shard over BOTH data-parallel axes."""
        from jax.sharding import PartitionSpec as P
        return P((self.data_axis, self.fsdp_axis))

    def spec_for(self, name: str, shape: Optional[Sequence[int]] = None,
                 dtype=None):
        """THE parameter resolver: explicit overrides first (exact key,
        then regex fullmatch), then the name heuristic. Returns a
        PartitionSpec (``P()`` = replicated); never a spec the layout's
        own axis sizes cannot divide."""
        if self.overrides:
            from .mesh import resolve_layout_spec
            spec = resolve_layout_spec(dict(self.overrides), name)
            if spec is not None:
                return spec
        return parameter_spec_from_name(name, shape=shape, dtype=dtype,
                                        layout=self)

    # the callable-layout protocol (parallel.mesh.Layout): a bare
    # SpecLayout passed where a name->spec callable is expected resolves
    # shape-blind (replicated unless an override names the array);
    # shape-aware callers go through resolve_layout_spec(name, shape=)
    def __call__(self, name: str):
        return self.spec_for(name)


def parameter_spec_from_name(name: str,
                             shape: Optional[Sequence[int]] = None,
                             dtype=None,
                             layout: Optional[SpecLayout] = None):
    """Name-heuristic PartitionSpec (the SNIPPETS.md [2] pattern, made
    shape-safe): ``tp`` placement from the column/row rule tables, then
    ``fsdp`` on the largest remaining dim it divides — but only when the
    array is big enough (``min_shard_bytes``) and the dim divides
    exactly. Unknown shapes resolve replicated (always valid)."""
    from jax.sharding import PartitionSpec as P
    lo = layout or SpecLayout()
    if shape is None or len(shape) == 0:
        return P()
    shape = tuple(int(d) for d in shape)
    parts: list = [None] * len(shape)

    lname = name.lower()
    if lo.tp > 1 and len(shape) >= 2:
        tp_dim = None
        if any(r in lname for r in TP_COL_RULES):
            tp_dim = 0
        elif any(r in lname for r in TP_ROW_RULES):
            tp_dim = 1
        if tp_dim is not None and _divides(shape[tp_dim], lo.tp):
            parts[tp_dim] = lo.tp_axis

    itemsize = np.dtype(dtype or np.float32).itemsize
    nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize
    if lo.fsdp > 1 and nbytes >= lo.min_shard_bytes:
        # largest free dim the fsdp size divides (ties -> lowest index:
        # deterministic, and dim 0 is usually the output/stacking dim)
        best = None
        for i, d in enumerate(shape):
            if parts[i] is not None or not _divides(d, lo.fsdp):
                continue
            if best is None or d > shape[best]:
                best = i
        if best is not None:
            parts[best] = lo.fsdp_axis

    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def resolve_model_axis(mesh, legacy: str) -> str:
    """Default-axis resolution for the mode entry points (moe/pipeline/
    ring attention): the mode's legacy axis name (``expert``/``pipe``/
    ``sp``) when the mesh actually carries it — a mesh built with that
    axis was built FOR that mode, even if it also carries ``tp`` — else
    the canonical ``tp`` axis when present, else the legacy name (which
    then fails loudly at trace time on the missing axis)."""
    names = set(str(a) for a in mesh.axis_names)
    if legacy in names:
        return legacy
    canonical = SpecLayout().tp_axis
    if canonical in names:
        return canonical
    return legacy


# ------------------------------------------------------------- the islands

def island_specs(island: str,
                 layout: Optional[SpecLayout] = None) -> Dict[str, Any]:
    """Canonical layout claims per parallel island, ALL drawn from one
    ``SpecLayout`` — the same logical name maps to the same spec in
    every island, and every axis exists on the canonical mesh, so
    ``analysis.sharding_passes.check_islands`` reports zero
    disagreements (the unification test pins this)."""
    from jax.sharding import PartitionSpec as P
    lo = layout or SpecLayout()
    batch = lo.batch_spec()
    model = lo.tp_axis
    param = P(lo.fsdp_axis)
    table = {
        # data parallel + FSDP: batch over (data, fsdp); parameters and
        # optimizer states sharded over fsdp (replicated when fsdp=1)
        "mesh": {"batch": batch, "param": param},
        # the dist data plane reduces gradients over the SAME dp axes
        # the batch shards over; parameter residency follows mesh's claim
        "dist": {"batch": batch, "param": param},
        # expert parallel: the expert dim of dispatched activations and
        # expert FFN weights rides the model axis (all_to_all over tp)
        "moe": {"batch": batch,
                "expert_in": P(model, None, None),
                "expert_out": P(model, None, None),
                "expert_param": P(model, None, None)},
        # pipeline: stacked per-stage params shard their leading stage
        # axis over the model axis; activations hop via ppermute
        "pipeline": {"batch": batch, "stage_params": P(model)},
        # sequence parallel: q/k/v shard the sequence dim over the model
        # axis ((B, H, S, D) layout)
        "ring_attention": {"batch": batch,
                           "qkv_seq": P(None, None, model, None)},
        # generative serving: the decode KV cache shards its head axis
        # over the model axis ((slots, H, S, D) layout — the serving
        # analogue of tp-sharded attention heads); the int8 per-page
        # scale planes (slots, H, n_pages) follow the same head split
        "serve": {"batch": batch,
                  "kv_cache": P(None, model, None, None),
                  "kv_scale": P(None, model, None)},
    }
    if island not in table:
        raise ValueError("unknown sharding island %r (have %s)"
                         % (island, sorted(table)))
    return table[island]


# checkpoint keys are prefixed ("arg:fc1_weight", "opt:fc1_weight.0",
# "aux:bn_moving_mean"); layout resolution must see the parameter name
# so optimizer-state leaves follow their parameter's spec
_CKPT_KEY_RE = re.compile(r"^(arg|aux|opt):(?P<name>[^.]+)")


def strip_ckpt_key(name: str) -> Optional[str]:
    """``arg:fc1_weight`` / ``opt:fc1_weight.0.1`` -> ``fc1_weight``;
    None for keys that are not parameter-backed (``rng:*``, ``upd:*`` —
    those stay replicated under a SpecLayout)."""
    m = _CKPT_KEY_RE.match(name)
    return m.group("name") if m else None

"""Expert parallelism: mixture-of-experts FFN over an ``expert`` mesh axis.

The reference has no MoE (SURVEY.md §2.21 marks expert parallel absent);
this is the modern capability the TPU build adds on top of parity. The
design is the TPU-idiomatic dense-dispatch form (Switch Transformer /
GShard): routing builds dispatch/combine tensors, expert inputs are
gathered with an einsum, and ``with_sharding_constraint`` pins the expert
dimension to the ``expert`` mesh axis — XLA/GSPMD then lowers the two
dispatch einsums to ``all_to_all`` collectives over ICI. No hand-written
comms; everything stays differentiable and jit-compatible.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["moe_init", "moe_apply", "sharding_island"]


def sharding_island():
    """Canonical layout claims of the expert-parallel island (audited by
    ``analysis.sharding_passes.check_islands``): drawn from the unified
    SpecLayout — tokens arrive batch-sharded over ``(data, fsdp)`` like
    everywhere else, and the expert dimension rides the canonical ``tp``
    model axis (the all_to_all dispatch axis), so the audit reports zero
    cross-island disagreements."""
    from .layout import island_specs
    return "moe", island_specs("moe")


def moe_init(rng, d_model: int, d_hidden: int, n_experts: int, dtype=None):
    """Initialize router + expert FFN parameters.

    Returns {"router": (d, E), "wi": (E, d, h), "wo": (E, h, d)}.
    """
    import numpy as np
    dtype = dtype or np.float32
    s_in = 1.0 / np.sqrt(d_model)
    s_hid = 1.0 / np.sqrt(d_hidden)
    return {
        "router": (rng.normal(0, s_in, (d_model, n_experts))).astype(dtype),
        "wi": (rng.normal(0, s_in, (n_experts, d_model, d_hidden))
               ).astype(dtype),
        "wo": (rng.normal(0, s_hid, (n_experts, d_hidden, d_model))
               ).astype(dtype),
    }


def moe_apply(params, x, *, top_k: int = 2, capacity_factor: float = 1.25,
              mesh=None, axis: Optional[str] = None):
    """Apply the MoE FFN to tokens ``x`` of shape (tokens, d_model).

    Routing is top-``top_k`` softmax gating with per-expert capacity
    ``C = ceil(tokens * top_k * capacity_factor / E)``; tokens over
    capacity at an expert are dropped for that expert (standard Switch
    semantics — gate mass is renormalized over surviving assignments).

    Under ``jit`` with ``mesh``, the expert dimension of the dispatched
    activations is sharded over ``axis`` so each device runs only its
    experts; the surrounding einsums become all_to_all + local matmul.
    ``axis=None`` resolves to the legacy ``expert`` axis when the mesh
    carries it, else the unified SpecLayout's model axis (``tp``).
    Returns (tokens, d_model) combined outputs plus the load-balancing
    auxiliary loss (GShard aux: E * sum_e f_e * p_e).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if mesh is not None:
        if axis is None:
            from .layout import resolve_model_axis
            axis = resolve_model_axis(mesh, "expert")
        elif axis not in mesh.axis_names:
            raise ValueError("mesh has no axis %r (axes: %s)"
                             % (axis, tuple(mesh.axis_names)))
    T, D = x.shape
    E = params["router"].shape[1]
    k = min(top_k, E)
    C = max(1, int(-(-T * k * capacity_factor // E)))  # ceil

    logits = x @ params["router"]                      # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)      # (T, k)

    # position of each (token, choice) in its expert's capacity buffer:
    # count prior assignments to the same expert in (token, choice) order.
    # Bookkeeping must stay int32: bf16 activations can't represent counts
    # above 256, which silently corrupts capacity slots for T*k > 256.
    choice_mask_i = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # (T, k, E)
    flat = choice_mask_i.reshape(T * k, E)
    pos = jnp.cumsum(flat, axis=0) - flat              # (T*k, E)
    pos = jnp.sum(pos * flat, axis=-1).reshape(T, k)   # (T, k)
    choice_mask = choice_mask_i.astype(x.dtype)
    keep = (pos < C).astype(x.dtype)
    gate_vals = gate_vals * keep
    denom = jnp.sum(gate_vals, axis=-1, keepdims=True)
    gate_vals = gate_vals / jnp.maximum(denom, 1e-9)

    pos_oh = jax.nn.one_hot(pos, C, dtype=x.dtype)     # (T, k, C)
    # (T, E, C) combine weights; dispatch is its 0/1 support
    combine = jnp.einsum("tke,tk,tkc->tec", choice_mask, gate_vals, pos_oh)
    dispatch = jnp.einsum("tke,tk,tkc->tec", choice_mask, keep, pos_oh)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)  # (E, C, D)
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, jax.sharding.NamedSharding(mesh, P(axis, None, None)))
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, params["wi"]))
    expert_out = jnp.einsum("ech,ehd->ecd", h, params["wo"])
    if mesh is not None:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, jax.sharding.NamedSharding(mesh, P(axis, None, None)))
    out = jnp.einsum("tec,ecd->td", combine, expert_out)

    # GShard load-balance aux loss: fraction routed vs mean gate prob
    frac = jnp.mean(choice_mask[:, 0, :], axis=0)      # top-1 routing share
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return out, aux

"""mx.parallel — mesh sharding, collectives and sequence parallelism.

The TPU-native replacement for the reference's distribution stack
(SURVEY.md §2.7 KVStore comm, §2.12 ps-lite, §2.21 parallelism checklist):

* data parallel  → batch sharded over a ``data`` mesh axis (mesh.py)
* FSDP / ZeRO → params + optimizer states sharded over ``fsdp`` (layout.py)
* tensor parallel → parameters sharded over the ``tp`` axis (GSPMD)
* model parallel (group2ctx) → per-arg device shardings (executor.py)
* pipeline parallel → GPipe microbatch schedule over a mesh axis (pipeline.py)
* expert parallel → MoE with all_to_all token dispatch (moe.py)
* sequence parallel / long context → ring attention (ring_attention.py)
* multi-host → ``jax.distributed`` + the same mesh spanning hosts

ONE layout ties them together (ROADMAP item 1): :class:`SpecLayout`
(layout.py) is the canonical ``data x fsdp x tp`` mesh + PartitionSpec
policy every island declares its claims in — ``Module.set_layout`` /
``fit(layout=)`` consume it, checkpoint reshard-on-load resolves through
the same funnel, and ``analysis audit islands`` pins the agreement.
"""
from .mesh import (make_mesh, data_parallel_mesh, batch_sharding,
                   replicated_sharding, shard_batch, replicate, P, Mesh,
                   NamedSharding, mesh_devices)
from .ring_attention import (ring_attention, ring_self_attention,
                             local_attention_block)
from .pipeline import pipeline_apply, pipeline_1f1b, stack_stage_params
from .moe import moe_init, moe_apply

__all__ = ["make_mesh", "data_parallel_mesh", "batch_sharding",
           "replicated_sharding", "shard_batch", "replicate", "P", "Mesh",
           "NamedSharding", "mesh_devices", "ring_attention",
           "ring_self_attention", "local_attention_block",
           "pipeline_apply", "pipeline_1f1b", "stack_stage_params",
           "moe_init", "moe_apply", "sharding_islands",
           "SpecLayout", "parameter_spec_from_name"]


def __getattr__(name):
    # layout.py loads lazily (PEP 562): mxnet_tpu/__init__ imports this
    # package eagerly, and the zero-cost contract is that a plain fit
    # (no layout set) never imports the layout module at all — the CI
    # multichip smoke asserts sys.modules stays clean
    if name in ("SpecLayout", "parameter_spec_from_name"):
        from . import layout as _layout
        return getattr(_layout, name)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))


def sharding_islands():
    """Every parallel mode's canonical layout claims, keyed by island
    name — the input of ``analysis.sharding_passes.check_islands``.
    Since the SpecLayout unification (ROADMAP item 1) every island draws
    its claims from the ONE ``data x fsdp x tp`` layout, so the audit
    reports zero disagreements; the audit stays wired so any future
    island that drifts from the canonical layout becomes a finding, not
    a multi-chip bill."""
    # NOTE: `from . import ring_attention` would return the FUNCTION of
    # the same name re-exported above, not the submodule — import the
    # island declarations directly
    from .mesh import sharding_island as _mesh_island
    from .dist import sharding_island as _dist_island
    from .moe import sharding_island as _moe_island
    from .pipeline import sharding_island as _pipe_island
    from .ring_attention import sharding_island as _ring_island
    islands = {}
    for fn in (_mesh_island, _dist_island, _moe_island, _pipe_island,
               _ring_island):
        name, specs = fn()
        islands[name] = specs
    return islands

"""mx.parallel — mesh sharding, collectives and sequence parallelism.

The TPU-native replacement for the reference's distribution stack
(SURVEY.md §2.7 KVStore comm, §2.12 ps-lite, §2.21 parallelism checklist):

* data parallel  → batch sharded over a ``data`` mesh axis (mesh.py)
* tensor parallel → parameters sharded over a ``model`` axis (GSPMD)
* model parallel (group2ctx) → per-arg device shardings (executor.py)
* pipeline parallel → GPipe microbatch schedule over a mesh axis (pipeline.py)
* expert parallel → MoE with all_to_all token dispatch (moe.py)
* sequence parallel / long context → ring attention (ring_attention.py)
* multi-host → ``jax.distributed`` + the same mesh spanning hosts
"""
from .mesh import (make_mesh, data_parallel_mesh, batch_sharding,
                   replicated_sharding, shard_batch, replicate, P, Mesh,
                   NamedSharding, mesh_devices)
from .ring_attention import (ring_attention, ring_self_attention,
                             local_attention_block)
from .pipeline import pipeline_apply, pipeline_1f1b, stack_stage_params
from .moe import moe_init, moe_apply

__all__ = ["make_mesh", "data_parallel_mesh", "batch_sharding",
           "replicated_sharding", "shard_batch", "replicate", "P", "Mesh",
           "NamedSharding", "mesh_devices", "ring_attention",
           "ring_self_attention", "local_attention_block",
           "pipeline_apply", "pipeline_1f1b", "stack_stage_params",
           "moe_init", "moe_apply", "sharding_islands"]


def sharding_islands():
    """Every parallel mode's canonical layout claims, keyed by island
    name — the input of ``analysis.sharding_passes.check_islands``.
    Until ROADMAP item 1 unifies these behind one SpecLayout, the
    islands legitimately disagree (each assumes its own mesh axis and
    its own batch layout); the audit keeps those disagreements *visible*
    instead of discovered on a multi-chip bill."""
    # NOTE: `from . import ring_attention` would return the FUNCTION of
    # the same name re-exported above, not the submodule — import the
    # island declarations directly
    from .mesh import sharding_island as _mesh_island
    from .moe import sharding_island as _moe_island
    from .pipeline import sharding_island as _pipe_island
    from .ring_attention import sharding_island as _ring_island
    islands = {}
    for fn in (_mesh_island, _moe_island, _pipe_island, _ring_island):
        name, specs = fn()
        islands[name] = specs
    return islands

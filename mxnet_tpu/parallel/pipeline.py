"""Pipeline parallelism: GPipe-style microbatched schedule over a mesh axis.

TPU-native upgrade of the reference's inter-layer model parallelism
(``group2ctx`` + PlaceDevice inserting _CrossDeviceCopy nodes,
src/executor/graph_executor.cc:279-393, demo example/model-parallel-lstm/
lstm.py:65-129). The reference overlaps stages only through its dependency
engine; here the schedule is explicit SPMD: every device runs the same
program under ``shard_map``, holds one stage's parameters (stacked pytree
sharded over the ``pipe`` axis), and microbatch activations hop stages via
``lax.ppermute`` over ICI. ``M`` microbatches over ``N`` stages take
``M + N - 1`` ticks (the GPipe bubble); everything is a ``lax.scan`` so XLA
sees one compiled loop, and the whole thing is differentiable (``ppermute``
has a transpose rule) so ``jax.grad`` of a pipelined loss just works —
gradients accumulate across microbatches exactly like GPipe.

Heterogeneous models (embed -> blocks -> logits/loss) fit the SPMD
uniformity requirement through ``first_fn``/``last_fn``: the repeated
``stage_fn`` maps a fixed "wire" activation shape to itself, while the
first/last stages adapt raw inputs to the wire and the wire to outputs.
Their (replicated) computations run on every device and are masked to
the owning stage — the standard GPipe-under-SPMD trick: uniformity costs
a little redundant embed/head compute, and buys one compiled program.
"""
from __future__ import annotations

import functools

__all__ = ["pipeline_apply", "pipeline_1f1b", "stack_stage_params",
           "sharding_island"]


def sharding_island():
    """Canonical layout claims of the pipeline island (audited by
    ``analysis.sharding_passes.check_islands``): drawn from the unified
    SpecLayout — the stacked stage-parameter axis rides the canonical
    ``tp`` model axis and the batch layout matches every other island,
    so the audit reports zero cross-island disagreements."""
    from .layout import island_specs
    return "pipeline", island_specs("pipeline")


def _resolve_axis(mesh, axis):
    """``axis=None`` resolves to the legacy ``pipe`` axis when the mesh
    carries it, else the unified SpecLayout's model axis (``tp``) —
    meshes built with a ``pipe`` axis keep working. An explicit axis is
    honored verbatim and must exist on the mesh (typos fail loudly
    instead of silently redirecting to another axis)."""
    if axis is not None:
        if axis not in mesh.axis_names:
            raise ValueError("mesh has no axis %r (axes: %s)"
                             % (axis, tuple(mesh.axis_names)))
        return axis
    from .layout import resolve_model_axis
    return resolve_model_axis(mesh, "pipe")


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees along a new leading axis.

    The result is what ``pipeline_apply`` expects: each leaf has shape
    ``(n_stages, ...)``; shard the leading axis over the pipe mesh axis.
    All stages must share one parameter structure (equal blocks per
    stage — the usual pipeline layout); adapters that don't fit it go in
    ``first_fn``/``last_fn`` params instead.
    """
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_apply(stage_fn, stage_params, inputs, *, mesh, axis=None,
                   first_fn=None, first_params=None,
                   last_fn=None, last_params=None, remat=False):
    """Run ``N = mesh.shape[axis]`` pipeline stages over microbatched input.

    Parameters
    ----------
    stage_fn : callable(params_i, x) -> y
        The per-stage computation; ``y`` must have ``x``'s shape/dtype
        (the pipeline "wire"), so activations can hop devices uniformly.
    stage_params : pytree
        Per-stage parameters stacked on a leading ``n_stages`` axis
        (see ``stack_stage_params``).
    inputs : array or pytree of arrays, each (M, mb, ...)
        ``M`` microbatches. ``M >= N`` keeps the bubble fraction at
        ``(N-1)/(M+N-1)``. A pytree (e.g. ``{"data": ..., "label": ...}``)
        lets the head see per-microbatch side inputs; a bare array is the
        wire itself when ``first_fn`` is None.
    mesh : jax.sharding.Mesh with the ``axis`` dimension.
    first_fn : callable(first_params, raw_mb) -> wire, optional
        Input adapter owned by stage 0 (e.g. embedding lookup: int token
        ids -> hidden states). Its output defines the wire shape/dtype.
        ``first_params`` ride replicated. ``raw_mb`` is the microbatch
        slice of ``inputs`` (same pytree structure).
    last_fn : callable(last_params, wire[, raw_mb]) -> out, optional
        Output head owned by stage N-1 (e.g. final norm + logits, or a
        per-microbatch loss). Defines the returned shape. A 3-argument
        ``last_fn`` also receives the microbatch slice of ``inputs``
        whose wire is finishing — how labels reach a loss head.
    remat : bool
        Wrap ``stage_fn`` in ``jax.checkpoint`` so backward recomputes
        stage activations per microbatch instead of storing all
        ``M x N`` of them (GPipe's activation memory trade).

    Returns the (M, ...) per-microbatch outputs of ``last_fn`` (or of the
    last stage when ``last_fn`` is None).
    """
    import inspect
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    axis = _resolve_axis(mesh, axis)
    n_stages = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(inputs)
    n_micro = leaves[0].shape[0]
    if n_micro < 1:
        raise ValueError("need at least one microbatch")
    tree_mb = lambda xs, t: jax.tree_util.tree_map(lambda a: a[t], xs)

    # heterogeneous stages: a list of per-stage fns with a tuple of
    # per-stage param trees (structures may differ). Each device runs its
    # own branch via lax.switch; params ride replicated (P()) since a
    # ragged tuple cannot shard over the pipe axis — the activation
    # schedule still pipelines. Homogeneous callers keep the stacked,
    # param-sharded fast path.
    hetero = isinstance(stage_fn, (list, tuple))
    if hetero:
        if len(stage_fn) != n_stages:
            raise ValueError("got %d stage fns for %d pipeline devices"
                             % (len(stage_fn), n_stages))
        stage_fns = [jax.checkpoint(f) if remat else f for f in stage_fn]
    elif remat:
        stage_fn = jax.checkpoint(stage_fn)

    # a 3-arg head also sees the finishing microbatch's raw inputs
    # (labels for a loss head); keep the 2-arg form working
    if last_fn is not None and \
            len(inspect.signature(last_fn).parameters) >= 3:
        head_fn = last_fn
    elif last_fn is not None:
        head_fn = lambda p, y, raw: last_fn(p, y)
    else:
        head_fn = None

    # wire shape: what hops between devices each tick
    raw_sd = jax.eval_shape(lambda x: tree_mb(x, 0), inputs)
    if first_fn is None:
        wire_sd = raw_sd
        if not isinstance(wire_sd, jax.ShapeDtypeStruct):
            raise ValueError(
                "pytree inputs need a first_fn to define the wire")
    else:
        wire_sd = jax.eval_shape(first_fn, first_params, raw_sd)
    out_sd = wire_sd if head_fn is None else \
        jax.eval_shape(head_fn, last_params, wire_sd, raw_sd)

    # params: leading stage axis sharded over the pipe axis; inputs,
    # outputs, and the first/last adapters replicated (only stage 0
    # reads, only stage N-1 writes — jnp.where keeps SPMD uniform).
    rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
    param_spec = rep(stage_params) if hetero else \
        jax.tree_util.tree_map(lambda _: P(axis), stage_params)

    def spmd(params, fparams, lparams, xs):
        idx = lax.axis_index(axis)
        if hetero:
            local = params          # full tuple; switch picks the branch
            run_stage = lambda x: lax.switch(
                idx, [lambda op, k=k: stage_fns[k](op[0][k], op[1])
                      for k in range(n_stages)], (local, x))
        else:
            # this device's stage params: shard_map hands us a leading
            # axis of size n_stages/n_stages == 1
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            run_stage = lambda x: stage_fn(local, x)
        ticks = n_micro + n_stages - 1

        def step(carry, t):
            recv, outs = carry
            raw = tree_mb(xs, jnp.clip(t, 0, n_micro - 1))
            z0 = raw if first_fn is None else first_fn(fparams, raw)
            x = jnp.where(idx == 0, z0, recv)
            y = run_stage(x)
            # device i hands its activation to i+1 (the last stage's
            # output stays home and is collected below)
            send = lax.ppermute(
                y, axis, perm=[(i, i + 1) for i in range(n_stages - 1)])
            out_t = t - (n_stages - 1)
            raw_out = tree_mb(xs, jnp.clip(out_t, 0, n_micro - 1))
            take = jnp.logical_and(idx == n_stages - 1,
                                   jnp.logical_and(out_t >= 0,
                                                   out_t < n_micro))
            if head_fn is None:
                out = y
            else:
                # the head must run ONLY on collected ticks — not just be
                # masked after the fact. Loss heads (SoftmaxOutput et al.)
                # have custom vjps that ignore the incoming cotangent, so
                # a merely-masked head would inject a gradient from every
                # bubble/garbage tick on every device; lax.cond keeps the
                # untaken branch out of both forward and backward.
                out = lax.cond(
                    take,
                    lambda args: head_fn(lparams, *args),
                    lambda args: jnp.zeros(out_sd.shape, out_sd.dtype),
                    (y, raw_out))
            slot = jnp.clip(out_t, 0, n_micro - 1)
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(take, out, lax.dynamic_index_in_dim(
                    outs, slot, 0, keepdims=False)),
                slot, 0)
            return (send, outs), None

        init = (jnp.zeros(wire_sd.shape, wire_sd.dtype),
                jnp.zeros((n_micro,) + out_sd.shape, out_sd.dtype))
        (_, outs), _ = lax.scan(step, init, jnp.arange(ticks))
        # everyone returns; only the last stage's buffer is real —
        # psum after masking replicates it across the pipe axis
        outs = jnp.where(idx == n_stages - 1, outs, 0)
        return lax.psum(outs, axis)

    fn = shard_map(spmd, mesh=mesh,
                   in_specs=(param_spec, rep(first_params),
                             rep(last_params), P()),
                   out_specs=P(), check_rep=False)
    return fn(stage_params, first_params, last_params, inputs)


def pipeline_1f1b(stage_fns, stage_params, inputs, *, mesh, axis=None,
                  first_fn, first_params, last_fn, last_params, key=None,
                  stage_aux=None):
    """One-forward-one-backward pipeline schedule with a hand-written
    backward (PipeDream-flush class; the modern upgrade of GPipe's
    all-forward-then-all-backward).

    Unlike :func:`pipeline_apply` (whose backward is jax autodiff of the
    forward scan, so all ``M`` microbatch residuals stay live), this
    schedules forward and backward ticks on one lattice: at tick ``t``
    device ``i`` runs the forward of microbatch ``t - i`` and the
    backward of microbatch ``t - (2N-2-i)``, recomputing the stage
    forward from a saved input (activation-remat) for the vjp. Saved
    inputs live in a ring buffer of ``min(M, 2N-1)`` slots — activation
    memory is O(N), not O(M), which is the point of 1F1B. The bubble is
    ``(2N-2)/(M+2N-2)`` of ticks (each tick = 1 fwd + 1 recompute +
    1 bwd), vs GPipe's ``(N-1)/(M+N-1)`` per direction — slightly more
    idle, bounded memory.

    Because the backward is hand-scheduled, this function returns
    gradients directly (do NOT wrap it in ``jax.grad``):

    ``outs, grads = pipeline_1f1b(...)`` where ``grads`` is
    ``{"first": tree, "stages": tuple_of_trees, "last": tree}`` —
    f32-accumulated sums over microbatches, seeded with ones at each
    microbatch's head output (Module backward semantics: loss ops'
    custom vjps define the cotangent and may ignore the seed).

    Parameters mirror :func:`pipeline_apply`'s heterogeneous form:
    ``stage_fns`` is a list of ``fn(params_i, x, key) -> y`` (wire-shaped
    y), ``stage_params`` a tuple of per-stage trees (replicated across
    the mesh — ragged trees cannot shard), ``first_fn(fp, raw, key)``,
    ``last_fn(lp, y, raw, key)``. ``key`` is folded with the microbatch
    index so dropout differs per microbatch and the backward recompute
    replays the forward's randomness exactly.

    ``stage_aux`` (optional): a tuple of per-stage auxiliary-state trees
    (BatchNorm moving stats). When given, stage fns take the 4-ary form
    ``fn(params_i, aux_i, x, key) -> (y, new_aux_i)``; each forward tick
    updates the owning stage's aux (running stats advance once per
    microbatch, like a sequential run), the backward recompute uses the
    tick-current aux, and the final aux tuple is returned:
    ``outs, grads, new_aux = pipeline_1f1b(..., stage_aux=aux)``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    axis = _resolve_axis(mesh, axis)
    N = mesh.shape[axis]
    # a single callable = homogeneous stacked mode: params/aux leaves
    # carry a leading N axis SHARDED over the pipe axis (same layout as
    # pipeline_apply's fast path) — parameter memory scales, unlike the
    # replicated tuple mode that ragged (heterogeneous) stages need
    stacked = callable(stage_fns)
    lift = lambda f: lambda p, a, x, kk: (f(p, x, kk), a)
    has_aux = stage_aux is not None
    if stacked:
        if not has_aux:
            stage_aux = {}
            stage_fns = lift(stage_fns)
    else:
        if len(stage_fns) != N:
            raise ValueError("got %d stage fns for %d pipeline devices"
                             % (len(stage_fns), N))
        if not has_aux:
            stage_aux = tuple({} for _ in range(N))
            stage_fns = [lift(f) for f in stage_fns]
    leaves = jax.tree_util.tree_leaves(inputs)
    M = leaves[0].shape[0]
    if key is None:
        key = jax.random.PRNGKey(0)
    tree_mb = lambda xs, t: jax.tree_util.tree_map(lambda a: a[t], xs)

    raw_sd = jax.eval_shape(lambda x: tree_mb(x, 0), inputs)
    key_sd = jax.eval_shape(lambda k: k, key)
    wire_sd = jax.eval_shape(first_fn, first_params, raw_sd, key_sd)
    out_sd = jax.eval_shape(last_fn, last_params, wire_sd, raw_sd, key_sd)

    BUF = min(M, 2 * N - 1)
    ticks = M + 2 * N - 2
    rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
    f32zeros = lambda tree: jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), tree)
    gate = lambda cond_, tree: jax.tree_util.tree_map(
        lambda g: jnp.where(cond_, g, 0.0).astype(jnp.float32), tree)
    acc = lambda a, b: jax.tree_util.tree_map(
        lambda x, y: x + y.astype(jnp.float32), a, b)

    def spmd(params, aux0, fparams, lparams, xs, key):
        idx = lax.axis_index(axis)
        if stacked:
            # my stage's slice of the P(axis)-sharded stacked trees
            local_p = jax.tree_util.tree_map(lambda a: a[0], params)
            local_a0 = jax.tree_util.tree_map(lambda a: a[0], aux0)

            def run_fwd(op):
                _, aux, x, kk = op
                return stage_fns(local_p, aux, x, kk)

            def run_vjp(op):
                _, aux, x, kk, cot = op
                y, pull, _ = jax.vjp(
                    lambda pk, xx: stage_fns(pk, aux, xx, kk),
                    local_p, x, has_aux=True)
                gp, dx = pull(cot.astype(y.dtype))
                return jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), gp), dx
        else:
            local_a0 = aux0

            def fwd_branch(k):
                def run(op):
                    p, aux, x, kk = op
                    y, new_aux_k = stage_fns[k](p[k], aux[k], x, kk)
                    out_aux = list(aux)
                    out_aux[k] = new_aux_k
                    return y, tuple(out_aux)
                return run

            def vjp_branch(k):
                def run(op):
                    p, aux, x, kk, cot = op
                    y, pull, _ = jax.vjp(
                        lambda pk, xx: stage_fns[k](pk, aux[k], xx, kk),
                        p[k], x, has_aux=True)
                    gp_k, dx = pull(cot.astype(y.dtype))
                    gp = list(f32zeros(params))
                    gp[k] = jax.tree_util.tree_map(
                        lambda g: g.astype(jnp.float32), gp_k)
                    return tuple(gp), dx
                return run

            def run_fwd(op):
                return lax.switch(idx, [fwd_branch(k) for k in range(N)],
                                  op)

            def run_vjp(op):
                return lax.switch(idx, [vjp_branch(k) for k in range(N)],
                                  op)

        def head_vjp(op):
            lp, y, raw, kk = op
            out, pull = jax.vjp(
                lambda l, yy: last_fn(l, yy, raw, kk), lp, y)
            gl, cot = pull(jnp.ones(out.shape, out.dtype))
            return (out,
                    jax.tree_util.tree_map(
                        lambda g: g.astype(jnp.float32), gl),
                    cot.astype(jnp.float32))

        def head_zero(op):
            return (jnp.zeros(out_sd.shape, out_sd.dtype),
                    f32zeros(lparams),
                    jnp.zeros(wire_sd.shape, jnp.float32))

        def first_vjp(op):
            fp, raw, kk, dx = op
            z, pull = jax.vjp(lambda f: first_fn(f, raw, kk), fp)
            (gf,) = pull(dx.astype(z.dtype))
            return jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), gf)

        def first_zero(op):
            return f32zeros(fparams)

        def step(carry, t):
            fwd_recv, bwd_recv, xbuf, aux_c, gF, gS, gL, outs = carry
            f = t - idx
            b = t - (2 * N - 2 - idx)
            do_f = jnp.logical_and(f >= 0, f < M)
            do_b = jnp.logical_and(b >= 0, b < M)
            raw_f = tree_mb(xs, jnp.clip(f, 0, M - 1))
            raw_b = tree_mb(xs, jnp.clip(b, 0, M - 1))
            key_f = jax.random.fold_in(key, jnp.clip(f, 0, M - 1))
            key_b = jax.random.fold_in(key, jnp.clip(b, 0, M - 1))
            # distinct keys per (microbatch, stage) — otherwise stages
            # built from one template drop identical dropout coordinates.
            # N / N+1 are the adapter's and head's reserved stage slots.
            kf_stage = jax.random.fold_in(key_f, idx)
            kb_stage = jax.random.fold_in(key_b, idx)
            kf_adapter = jax.random.fold_in(key_f, N)
            kb_adapter = jax.random.fold_in(key_b, N)
            kf_head = jax.random.fold_in(key_f, N + 1)

            # ---- forward tick: microbatch f through my stage
            z0 = first_fn(fparams, raw_f, kf_adapter)
            x_in = jnp.where(idx == 0, z0, fwd_recv)
            y, aux_new = run_fwd((params, aux_c, x_in, kf_stage))
            aux_c = jax.tree_util.tree_map(
                lambda new, old: jnp.where(do_f, new, old), aux_new,
                aux_c)
            slot_f = jnp.clip(f, 0, M - 1) % BUF
            old = lax.dynamic_index_in_dim(xbuf, slot_f, 0, keepdims=False)
            xbuf = lax.dynamic_update_index_in_dim(
                xbuf, jnp.where(do_f, x_in, old), slot_f, 0)

            # ---- head: runs only on the last device's valid fwd ticks
            # (lax.cond, not masking: loss vjps ignore the cotangent)
            take = jnp.logical_and(idx == N - 1, do_f)
            out_f, gl_t, cot_head = lax.cond(
                take, head_vjp, head_zero, (lparams, y, raw_f, kf_head))
            gL = acc(gL, gl_t)
            slot_o = jnp.clip(f, 0, M - 1)
            oldo = lax.dynamic_index_in_dim(outs, slot_o, 0,
                                            keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(take, out_f, oldo), slot_o, 0)

            # ---- backward tick: microbatch b (same-tick head cotangent
            # on the last device, else the cotangent from stage idx+1)
            cot_in = jnp.where(idx == N - 1, cot_head, bwd_recv)
            slot_b = jnp.clip(b, 0, M - 1) % BUF
            x_saved = lax.dynamic_index_in_dim(xbuf, slot_b, 0,
                                               keepdims=False)
            # the recompute uses the tick-current aux: in train mode BN
            # normalizes with batch statistics (aux only collects running
            # stats), so the recomputed activations are exact anyway
            gS_t, dx = run_vjp((params, aux_c, x_saved, kb_stage, cot_in))
            gS = acc(gS, gate(do_b, gS_t))
            gF = acc(gF, lax.cond(
                jnp.logical_and(idx == 0, do_b), first_vjp, first_zero,
                (fparams, raw_b, kb_adapter, dx)))

            fwd_send = lax.ppermute(
                y, axis, perm=[(i, i + 1) for i in range(N - 1)])
            bwd_send = lax.ppermute(
                dx.astype(jnp.float32), axis,
                perm=[(i, i - 1) for i in range(1, N)])
            return (fwd_send, bwd_send, xbuf, aux_c,
                    gF, gS, gL, outs), None

        init = (jnp.zeros(wire_sd.shape, wire_sd.dtype),
                jnp.zeros(wire_sd.shape, jnp.float32),
                jnp.zeros((BUF,) + wire_sd.shape, wire_sd.dtype),
                local_a0,
                f32zeros(fparams),
                f32zeros(local_p) if stacked else f32zeros(params),
                f32zeros(lparams),
                jnp.zeros((M,) + out_sd.shape, out_sd.dtype))
        (_, _, _, aux_c, gF, gS, gL, outs), _ = lax.scan(
            step, init, jnp.arange(ticks))
        # adapter/head grads live on devices 0 / N-1 and outs on the
        # last device — psum assembles them everywhere. Stage grads/aux:
        # stacked mode returns each device's slice (shard_map's P(axis)
        # out_spec reassembles the stacked trees); tuple mode masks the
        # non-owned slots and psums.
        outs = jnp.where(idx == N - 1, outs, 0)
        gL = jax.tree_util.tree_map(
            lambda g: jnp.where(idx == N - 1, g, 0.0), gL)
        gF = jax.tree_util.tree_map(
            lambda g: jnp.where(idx == 0, g, 0.0), gF)
        psum = lambda tree: jax.tree_util.tree_map(
            lambda v: lax.psum(v, axis), tree)
        if stacked:
            lead = lambda tree: jax.tree_util.tree_map(
                lambda v: v[None], tree)
            return psum(outs), psum(gF), lead(gS), psum(gL), lead(aux_c)
        aux_c = tuple(
            jax.tree_util.tree_map(
                lambda v: jnp.where(idx == k, v, 0.0), aux_c[k])
            for k in range(N))
        return psum(outs), psum(gF), psum(gS), psum(gL), psum(aux_c)

    if stacked:
        sh = lambda tree: jax.tree_util.tree_map(lambda _: P(axis), tree)
        stage_in_spec, stage_out_spec = sh(stage_params), \
            (sh(stage_params), sh(stage_aux))
    else:
        stage_in_spec = rep(stage_params)
        stage_out_spec = (rep(stage_params), rep(stage_aux))
    aux_in_spec = stage_out_spec[1]
    fn = shard_map(spmd, mesh=mesh,
                   in_specs=(stage_in_spec, aux_in_spec,
                             rep(first_params), rep(last_params),
                             P(), P()),
                   out_specs=(P(), rep(first_params), stage_out_spec[0],
                              rep(last_params), stage_out_spec[1]),
                   check_rep=False)
    outs, gF, gS, gL, new_aux = fn(stage_params, stage_aux,
                                   first_params, last_params,
                                   inputs, key)
    grads = {"first": gF, "stages": gS, "last": gL}
    if has_aux:
        return outs, grads, new_aux
    return outs, grads

"""Pipeline parallelism: GPipe-style microbatched schedule over a mesh axis.

TPU-native upgrade of the reference's inter-layer model parallelism
(``group2ctx`` + PlaceDevice inserting _CrossDeviceCopy nodes,
src/executor/graph_executor.cc:279-393, demo example/model-parallel-lstm/
lstm.py:65-129). The reference overlaps stages only through its dependency
engine; here the schedule is explicit SPMD: every device runs the same
program under ``shard_map``, holds one stage's parameters (stacked pytree
sharded over the ``pipe`` axis), and microbatch activations hop stages via
``lax.ppermute`` over ICI. ``M`` microbatches over ``N`` stages take
``M + N - 1`` ticks (the GPipe bubble); everything is a ``lax.scan`` so XLA
sees one compiled loop, and the whole thing is differentiable (``ppermute``
has a transpose rule) so ``jax.grad`` of a pipelined loss just works —
gradients accumulate across microbatches exactly like GPipe.

Heterogeneous models (embed -> blocks -> logits/loss) fit the SPMD
uniformity requirement through ``first_fn``/``last_fn``: the repeated
``stage_fn`` maps a fixed "wire" activation shape to itself, while the
first/last stages adapt raw inputs to the wire and the wire to outputs.
Their (replicated) computations run on every device and are masked to
the owning stage — the standard GPipe-under-SPMD trick: uniformity costs
a little redundant embed/head compute, and buys one compiled program.
"""
from __future__ import annotations

import functools

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees along a new leading axis.

    The result is what ``pipeline_apply`` expects: each leaf has shape
    ``(n_stages, ...)``; shard the leading axis over the pipe mesh axis.
    All stages must share one parameter structure (equal blocks per
    stage — the usual pipeline layout); adapters that don't fit it go in
    ``first_fn``/``last_fn`` params instead.
    """
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_apply(stage_fn, stage_params, inputs, *, mesh, axis="pipe",
                   first_fn=None, first_params=None,
                   last_fn=None, last_params=None, remat=False):
    """Run ``N = mesh.shape[axis]`` pipeline stages over microbatched input.

    Parameters
    ----------
    stage_fn : callable(params_i, x) -> y
        The per-stage computation; ``y`` must have ``x``'s shape/dtype
        (the pipeline "wire"), so activations can hop devices uniformly.
    stage_params : pytree
        Per-stage parameters stacked on a leading ``n_stages`` axis
        (see ``stack_stage_params``).
    inputs : array or pytree of arrays, each (M, mb, ...)
        ``M`` microbatches. ``M >= N`` keeps the bubble fraction at
        ``(N-1)/(M+N-1)``. A pytree (e.g. ``{"data": ..., "label": ...}``)
        lets the head see per-microbatch side inputs; a bare array is the
        wire itself when ``first_fn`` is None.
    mesh : jax.sharding.Mesh with the ``axis`` dimension.
    first_fn : callable(first_params, raw_mb) -> wire, optional
        Input adapter owned by stage 0 (e.g. embedding lookup: int token
        ids -> hidden states). Its output defines the wire shape/dtype.
        ``first_params`` ride replicated. ``raw_mb`` is the microbatch
        slice of ``inputs`` (same pytree structure).
    last_fn : callable(last_params, wire[, raw_mb]) -> out, optional
        Output head owned by stage N-1 (e.g. final norm + logits, or a
        per-microbatch loss). Defines the returned shape. A 3-argument
        ``last_fn`` also receives the microbatch slice of ``inputs``
        whose wire is finishing — how labels reach a loss head.
    remat : bool
        Wrap ``stage_fn`` in ``jax.checkpoint`` so backward recomputes
        stage activations per microbatch instead of storing all
        ``M x N`` of them (GPipe's activation memory trade).

    Returns the (M, ...) per-microbatch outputs of ``last_fn`` (or of the
    last stage when ``last_fn`` is None).
    """
    import inspect
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    n_stages = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(inputs)
    n_micro = leaves[0].shape[0]
    if n_micro < 1:
        raise ValueError("need at least one microbatch")
    tree_mb = lambda xs, t: jax.tree_util.tree_map(lambda a: a[t], xs)

    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    # a 3-arg head also sees the finishing microbatch's raw inputs
    # (labels for a loss head); keep the 2-arg form working
    if last_fn is not None and \
            len(inspect.signature(last_fn).parameters) >= 3:
        head_fn = last_fn
    elif last_fn is not None:
        head_fn = lambda p, y, raw: last_fn(p, y)
    else:
        head_fn = None

    # wire shape: what hops between devices each tick
    raw_sd = jax.eval_shape(lambda x: tree_mb(x, 0), inputs)
    if first_fn is None:
        wire_sd = raw_sd
        if not isinstance(wire_sd, jax.ShapeDtypeStruct):
            raise ValueError(
                "pytree inputs need a first_fn to define the wire")
    else:
        wire_sd = jax.eval_shape(first_fn, first_params, raw_sd)
    out_sd = wire_sd if head_fn is None else \
        jax.eval_shape(head_fn, last_params, wire_sd, raw_sd)

    # params: leading stage axis sharded over the pipe axis; inputs,
    # outputs, and the first/last adapters replicated (only stage 0
    # reads, only stage N-1 writes — jnp.where keeps SPMD uniform).
    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    rep = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)

    def spmd(params, fparams, lparams, xs):
        idx = lax.axis_index(axis)
        # this device's stage params: shard_map hands us a leading axis of
        # size n_stages/n_stages == 1
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        ticks = n_micro + n_stages - 1

        def step(carry, t):
            recv, outs = carry
            raw = tree_mb(xs, jnp.clip(t, 0, n_micro - 1))
            z0 = raw if first_fn is None else first_fn(fparams, raw)
            x = jnp.where(idx == 0, z0, recv)
            y = stage_fn(local, x)
            # device i hands its activation to i+1 (the last stage's
            # output stays home and is collected below)
            send = lax.ppermute(
                y, axis, perm=[(i, i + 1) for i in range(n_stages - 1)])
            out_t = t - (n_stages - 1)
            raw_out = tree_mb(xs, jnp.clip(out_t, 0, n_micro - 1))
            take = jnp.logical_and(idx == n_stages - 1,
                                   jnp.logical_and(out_t >= 0,
                                                   out_t < n_micro))
            if head_fn is None:
                out = y
            else:
                # the head must run ONLY on collected ticks — not just be
                # masked after the fact. Loss heads (SoftmaxOutput et al.)
                # have custom vjps that ignore the incoming cotangent, so
                # a merely-masked head would inject a gradient from every
                # bubble/garbage tick on every device; lax.cond keeps the
                # untaken branch out of both forward and backward.
                out = lax.cond(
                    take,
                    lambda args: head_fn(lparams, *args),
                    lambda args: jnp.zeros(out_sd.shape, out_sd.dtype),
                    (y, raw_out))
            slot = jnp.clip(out_t, 0, n_micro - 1)
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(take, out, lax.dynamic_index_in_dim(
                    outs, slot, 0, keepdims=False)),
                slot, 0)
            return (send, outs), None

        init = (jnp.zeros(wire_sd.shape, wire_sd.dtype),
                jnp.zeros((n_micro,) + out_sd.shape, out_sd.dtype))
        (_, outs), _ = lax.scan(step, init, jnp.arange(ticks))
        # everyone returns; only the last stage's buffer is real —
        # psum after masking replicates it across the pipe axis
        outs = jnp.where(idx == n_stages - 1, outs, 0)
        return lax.psum(outs, axis)

    fn = shard_map(spmd, mesh=mesh,
                   in_specs=(param_spec, rep(first_params),
                             rep(last_params), P()),
                   out_specs=P(), check_rep=False)
    return fn(stage_params, first_params, last_params, inputs)

"""Pipeline parallelism: GPipe-style microbatched schedule over a mesh axis.

TPU-native upgrade of the reference's inter-layer model parallelism
(``group2ctx`` + PlaceDevice inserting _CrossDeviceCopy nodes,
src/executor/graph_executor.cc:279-393, demo example/model-parallel-lstm/
lstm.py:65-129). The reference overlaps stages only through its dependency
engine; here the schedule is explicit SPMD: every device runs the same
program under ``shard_map``, holds one stage's parameters (stacked pytree
sharded over the ``pipe`` axis), and microbatch activations hop stages via
``lax.ppermute`` over ICI. ``M`` microbatches over ``N`` stages take
``M + N - 1`` ticks (the GPipe bubble); everything is a ``lax.scan`` so XLA
sees one compiled loop, and the whole thing is differentiable (``ppermute``
has a transpose rule) so ``jax.grad`` of a pipelined loss just works.
"""
from __future__ import annotations

import functools

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees along a new leading axis.

    The result is what ``pipeline_apply`` expects: each leaf has shape
    ``(n_stages, ...)``; shard the leading axis over the pipe mesh axis.
    """
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_apply(stage_fn, stage_params, inputs, *, mesh, axis="pipe"):
    """Run ``N = mesh.shape[axis]`` pipeline stages over microbatched input.

    Parameters
    ----------
    stage_fn : callable(params_i, x) -> y
        The per-stage computation; ``y`` must have ``x``'s shape/dtype
        (residual-block style), so activations can hop devices uniformly.
    stage_params : pytree
        Per-stage parameters stacked on a leading ``n_stages`` axis
        (see ``stack_stage_params``).
    inputs : array (M, mb, ...)
        ``M`` microbatches. ``M >= N`` keeps the bubble fraction at
        ``(N-1)/(M+N-1)``.
    mesh : jax.sharding.Mesh with the ``axis`` dimension.

    Returns the (M, mb, ...) outputs of the last stage.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    n_stages = mesh.shape[axis]
    n_micro = inputs.shape[0]
    if n_micro < 1:
        raise ValueError("need at least one microbatch")

    # params: leading stage axis sharded over the pipe axis; inputs and
    # outputs replicated (only stage 0 reads, only stage N-1 writes —
    # jnp.where keeps the SPMD program uniform).
    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)

    def spmd(params, xs):
        idx = lax.axis_index(axis)
        # this device's stage params: shard_map hands us a leading axis of
        # size n_stages/n_stages == 1
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        mb_shape = xs.shape[1:]
        ticks = n_micro + n_stages - 1

        def step(carry, t):
            recv, outs = carry
            x = jnp.where(idx == 0,
                          xs[jnp.clip(t, 0, n_micro - 1)], recv)
            y = stage_fn(local, x)
            # device i hands its activation to i+1 (the last stage's
            # output stays home and is collected below)
            send = lax.ppermute(
                y, axis, perm=[(i, i + 1) for i in range(n_stages - 1)])
            out_t = t - (n_stages - 1)
            take = jnp.logical_and(idx == n_stages - 1,
                                   jnp.logical_and(out_t >= 0,
                                                   out_t < n_micro))
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(take, y, lax.dynamic_index_in_dim(
                    outs, jnp.clip(out_t, 0, n_micro - 1), 0,
                    keepdims=False)),
                jnp.clip(out_t, 0, n_micro - 1), 0)
            return (send, outs), None

        init = (jnp.zeros(mb_shape, inputs.dtype),
                jnp.zeros((n_micro,) + mb_shape, inputs.dtype))
        (_, outs), _ = lax.scan(step, init, jnp.arange(ticks))
        # everyone returns; only the last stage's buffer is real —
        # psum after masking replicates it across the pipe axis
        outs = jnp.where(idx == n_stages - 1, outs, 0)
        return lax.psum(outs, axis)

    fn = shard_map(spmd, mesh=mesh, in_specs=(param_spec, P()),
                   out_specs=P(), check_rep=False)
    return fn(stage_params, inputs)

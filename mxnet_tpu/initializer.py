"""Weight initializers.

Reference: ``python/mxnet/initializer.py`` (726 LoC: InitDesc:34,
Uniform/Normal/Orthogonal, Xavier:545, MSRAPrelu, Bilinear, LSTMBias,
FusedRNN:676, Load/Mixed, attr-driven dispatch).
"""
from __future__ import annotations

import json
import logging
import re
from typing import Dict, Optional

import numpy as np

from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Orthogonal",
           "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "FusedRNN", "One",
           "Zero", "Constant", "Load", "Mixed", "register", "create"]

_INITIALIZER_REGISTRY: Dict[str, type] = {}


def register(klass):
    """(reference: initializer.py register / generic registry.py)."""
    _INITIALIZER_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs) -> "Initializer":
    if isinstance(name, Initializer):
        return name
    return _INITIALIZER_REGISTRY[name.lower()](**kwargs)


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (reference:
    initializer.py:34 — carries __init__ attr and global_init)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer(object):
    """Base initializer with name-pattern dispatch (reference:
    initializer.py Initializer.__call__: weight/bias/gamma/beta/
    moving_mean/moving_var/moving_avg special-casing)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._rng = None

    def set_rng(self, rng) -> "Initializer":
        """Route this initializer's random draws through an explicit
        numpy ``Generator`` instead of the process-global ``np.random``
        state — ``fit``'s default initializer passes one derived from
        the seeded ``mx.random`` key chain
        (``mx.random.derive_numpy_rng``), making identically-seeded runs
        draw identical initial weights. Returns ``self`` for chaining."""
        self._rng = rng
        return self

    @property
    def rng(self):
        """The random source draws come from: the generator installed by
        :meth:`set_rng`, else the legacy global ``np.random`` module
        (both expose ``uniform``/``normal``)."""
        return self._rng if self._rng is not None else np.random

    def dumps(self) -> str:
        """(reference: initializer.py dumps — JSON [name, kwargs])."""
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr: NDArray):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        if desc.attrs.get("__init__"):
            klass, kwargs = json.loads(desc.attrs["__init__"])
            create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("upsampling"):
            self._init_bilinear(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # ------------------------------------------------------- specializations
    def _init_bilinear(self, name, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = nd.array(weight.reshape(shape))

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "is now limited to weight/bias/gamma/beta. Use "
            "mx.sym.Variable(init=...) to set per-variable initializers." % name)


@register
class Load(object):
    """Init from an existing param dict, falling back to default_init
    (reference: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = nd.load(param)
        self.param = {}
        for name, arr in param.items():
            if name.startswith(("arg:", "aux:")):
                self.param[name[4:]] = arr
            else:
                self.param[name] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if arr.shape != self.param[name].shape:
                raise ValueError("Parameter %s shape mismatch: %s vs %s"
                                 % (name, arr.shape, self.param[name].shape))
            arr[:] = self.param[name]
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise ValueError("Cannot Initialize %s. Not found in loaded "
                                 "param and no default initializer" % name)
            self.default_init(name, arr)


@register
class Mixed(object):
    """Regex-pattern dispatch over sub-initializers (reference:
    initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must have same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError("Parameter %s did not match any pattern. Consider "
                         "adding a \".*\" pattern at the end." % name)


class _FillInitializer(Initializer):
    """Fill with one value for ANY name — but a per-variable ``init=`` attr
    still wins, so Variable(init=Normal()) is honored even when the global
    initializer is Zero (the attr dispatch in Initializer.__call__)."""

    _fill_value = 0.0

    def __call__(self, desc, arr):
        if isinstance(desc, InitDesc) and desc.attrs.get("__init__"):
            return Initializer.__call__(self, desc, arr)
        arr[:] = self._fill_value

    # the __init__-attr dispatch routes through _init_weight (reference:
    # initializer.py Zero/One define _init_weight)
    def _init_weight(self, name, arr):
        arr[:] = self._fill_value


@register
class Zero(_FillInitializer):
    _fill_value = 0.0


@register
class One(_FillInitializer):
    _fill_value = 1.0


@register
class Constant(_FillInitializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value
        self._fill_value = value


@register
class Uniform(Initializer):
    """U(-scale, scale) (reference: initializer.py Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = nd.array(self.rng.uniform(-self.scale, self.scale,
                                            arr.shape).astype(np.float32))


@register
class Normal(Initializer):
    """N(0, sigma) (reference: initializer.py Normal)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = nd.array(self.rng.normal(0, self.sigma,
                                           arr.shape).astype(np.float32))


@register
class Orthogonal(Initializer):
    """(reference: initializer.py Orthogonal — SVD of a gaussian)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = self.rng.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = self.rng.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        arr[:] = nd.array(self.scale * res.reshape(arr.shape).astype(np.float32))


@register
class Xavier(Initializer):
    """(reference: initializer.py:545 Xavier — uniform/gaussian over
    avg/in/out fans)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier initializer cannot be applied to vector "
                             "%s. It requires at least 2D." % name)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = nd.array(self.rng.uniform(-scale, scale,
                                                shape).astype(np.float32))
        elif self.rnd_type == "gaussian":
            arr[:] = nd.array(self.rng.normal(0, scale,
                                               shape).astype(np.float32))
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """Kaiming init accounting for PReLU slope (reference: initializer.py
    MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """(reference: initializer.py Bilinear — deconv upsampling kernels)."""

    def _init_weight(self, name, arr):
        Initializer._init_bilinear(self, name, arr)


@register
class LSTMBias(Initializer):
    """Zero bias except forget gate = forget_bias (reference:
    initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = nd.array(b)

    _init_bias = _init_weight


@register
class FusedRNN(Initializer):
    """Initialize the fused RNN op's packed parameter vector (reference:
    initializer.py:676 FusedRNN — per-gate init then pack). Weights get
    ``init`` (default Xavier), biases zero except the LSTM forget gate."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        super().__init__(init=init.dumps() if hasattr(init, "dumps")
                         else None, num_hidden=num_hidden,
                         num_layers=num_layers, mode=mode,
                         bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init if init is not None else Xavier()
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .ops.rnn_op import _GATES
        gates = _GATES[self._mode]
        dirs = 2 if self._bidirectional else 1
        H = self._num_hidden
        total = arr.size
        # solve input size from total (see FusedRNNCell._input_size_from)
        rest = (self._num_layers - 1) * dirs * gates * H * \
            (dirs * H + H + 2)
        input_size = (total - rest) // (dirs * gates * H) - H - 2
        out = np.zeros((total,), dtype=np.float32)
        p = 0
        for layer in range(self._num_layers):
            in_sz = input_size if layer == 0 else H * dirs
            for _ in range(dirs):
                for ni in (in_sz, H):
                    size = gates * H * ni
                    block = nd.zeros((gates * H, ni))
                    self._init(InitDesc(desc + "_weight", {}), block)
                    out[p:p + size] = block.asnumpy().ravel()
                    p += size
        for layer in range(self._num_layers):
            for _ in range(dirs):
                for _ in range(2):  # bx, bh
                    if self._mode == "lstm":
                        out[p + H:p + 2 * H] = self._forget_bias / 2.0
                    p += gates * H
        arr[:] = nd.array(out)


# name used by Variable(init=...) serialization
def from_json(s: str):
    klass, kwargs = json.loads(s)
    return create(klass, **kwargs)

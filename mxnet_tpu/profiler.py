"""``mx.profiler`` — execution tracing, counters/gauges/histograms, spans.

Reference: ``python/mxnet/profiler.py`` (profiler_set_config:27,
profiler_set_state:48, dump_profile:64) writing the chrome://tracing JSON
the engine emits in ``src/engine/profiler.cc:127-179``.

Four layers here (docs/architecture/observability.md):

* A framework-level event recorder: while the state is ``run``, every
  imperative op dispatch and every executor graph launch logs a
  chrome-trace complete event (synchronized — the op is blocked on so the
  duration is real device time, the profiler twin of the reference's
  engine sync mode). ``dump_profile()`` writes the standard
  ``{"traceEvents": [...]}`` JSON loadable in chrome://tracing / Perfetto.
* Structured **spans** with stable per-thread **lanes** and chrome-trace
  **flow events**: subsystems wrap their pipeline stages in
  ``span(name, flow=batch_id)`` so one batch's journey (prefetch →
  device-place → fused-step dispatch → metric sync → checkpoint write;
  serve: submit → coalesce → launch) renders as connected slices across
  threads. Spans are recorded while the profiler runs OR while the
  ``MXNET_TPU_OBS`` knob is on — otherwise ``span()`` returns a shared
  no-op and allocates nothing (the ``obs_spans`` counter asserts that).
* The XLA-level profiler: ``start_xla_trace(logdir)`` /
  ``stop_xla_trace()`` wrap ``jax.profiler`` for TensorBoard-grade HLO
  timelines on real hardware.
* Counters/gauges/histograms: always-on, string-keyed, thread-safe —
  used by subsystems to make their hot-path invariants assertable and
  exported in Prometheus text format by :mod:`mxnet_tpu.obs`. The
  checkpoint subsystem's family (docs/architecture/checkpoint.md):
  ``ckpt_block_us`` vs ``ckpt_write_us``, ``ckpt_saved`` / ``ckpt_bytes``
  / ``ckpt_save_async`` / ``ckpt_save_sync``, ``ckpt_backpressure_wait``,
  ``ckpt_write_failed``, ``ckpt_load_ok`` / ``ckpt_load_fallback``,
  ``ckpt_gc_removed``, ``ckpt_sigterm``, and gauges ``ckpt_queue_depth``,
  ``ckpt_last_block_ms``, ``ckpt_last_write_ms``.

Concurrency contract: every mutation of module state (``_state``,
``_filename``, events, counters, gauges, lanes, flow table) happens under
``_lock``. The hot paths read two cached module booleans (``_tracing``,
``_spans_on``) WITHOUT the lock as an early-out — a benign race whose
worst case is one event recorded just after ``set_state("stop")`` or one
skipped just after ``set_state("run")``; the authoritative append is
under the lock, so the event list and the dumped payload are always
internally consistent.
"""
from __future__ import annotations

import bisect
import itertools
import json
import threading
import time
from typing import Dict, List, Optional

from . import config as _config

__all__ = [
    "profiler_set_config", "profiler_set_state", "dump_profile",
    "set_config", "set_state", "dump", "pause", "resume",
    "start_xla_trace", "stop_xla_trace", "record_event", "state",
    "incr_counter", "get_counter", "counters", "reset_counters",
    "counter_delta",
    "set_gauge", "get_gauge", "gauges", "reset_gauges",
    "span", "record_span", "spans_enabled", "new_flow",
    "register_thread_lane", "set_span_listener", "blackbox",
    "Histogram", "histogram", "observe", "histograms", "reset_histograms",
]

_lock = threading.Lock()
_state = "stop"
_filename = "profile.json"
_events: List[dict] = []
_counters: dict = {}
_t0 = time.perf_counter()

# bound the in-memory trace: a long obs-on run must not grow without
# limit; overflow is counted so a truncated dump is detectable
_MAX_EVENTS = 1 << 20

# cached fast-path flags (see the concurrency contract above)
_tracing = False
_spans_on = False


def _recompute_enabled_locked() -> None:
    """Refresh the cached fast-path flags; caller holds ``_lock``."""
    global _tracing, _spans_on
    _tracing = _state == "run"
    _spans_on = _tracing or bool(_config.get("MXNET_TPU_OBS"))


def state() -> str:
    return _state


def set_config(filename: str = "profile.json", profile_all: bool = True,
               **_ignored) -> None:
    """(reference: profiler.py:27 profiler_set_config — mode knobs beyond
    the filename collapse: there is no per-subsystem engine here)."""
    global _filename
    with _lock:
        _filename = filename


def set_state(st: str = "stop") -> None:
    """'run' starts recording, 'stop' stops (reference: profiler.py:48)."""
    global _state
    assert st in ("run", "stop"), st
    with _lock:
        _state = st
        _recompute_enabled_locked()


def pause() -> None:
    set_state("stop")


def resume() -> None:
    set_state("run")


# --------------------------------------------------------------- lanes
# A lane is one timeline track in the trace (a chrome ``tid``). Usually a
# lane IS a thread (auto-registered under the thread's name on first
# event), but a pipeline stage that shares a thread may claim its own
# named lane (``span(..., lane="place")``) so its slices render on a
# separate track — the tid is a registered small integer either way,
# replacing the collision-prone ``tid % 100000`` of the original
# recorder. The registry survives ``dump(finished=True)`` so lane ids
# stay stable across dumps within one process.

_lanes: Dict[str, int] = {}            # lane name -> small stable id
_lane_counter = itertools.count(1)
_tls = threading.local()


def _lane_id_locked(name: str) -> int:
    lid = _lanes.get(name)
    if lid is None:
        lid = next(_lane_counter)
        _lanes[name] = lid
    return lid


def register_thread_lane(name: Optional[str] = None) -> int:
    """Name the calling thread's trace lane (defaults to the thread
    name); returns the stable lane id. Subsequent events from this thread
    land on that lane. Re-registering under a new name moves the thread
    to the (possibly fresh) lane."""
    if name is None:
        name = threading.current_thread().name
    with _lock:
        lid = _lane_id_locked(str(name))
    _tls.lane = lid
    return lid


def _current_lane_locked() -> int:
    lid = getattr(_tls, "lane", None)
    if lid is None:
        lid = _lane_id_locked(threading.current_thread().name)
        _tls.lane = lid
    return lid


# --------------------------------------------------------------- flows
# A flow id threads one logical unit of work (a batch, a request) through
# spans on different lanes; the dump carries chrome flow events ("s"
# start / "t" step) that Perfetto renders as arrows between the slices.

_flow_counter = itertools.count(1)
_flows_seen: Dict[int, bool] = {}
_MAX_FLOWS = 8192


def new_flow() -> int:
    """Allocate a process-unique flow id (cheap, lock-free)."""
    return next(_flow_counter)


def _flow_event_locked(fid: int, ts_us: float, lane: int) -> dict:
    if fid in _flows_seen:
        ph = "t"
    else:
        ph = "s"
        if len(_flows_seen) >= _MAX_FLOWS:
            # drop the oldest half: a stale flow re-appearing emits a
            # fresh "s" (one dangling arrow start, not a crash)
            for k in list(_flows_seen)[:_MAX_FLOWS // 2]:
                _flows_seen.pop(k, None)
        _flows_seen[fid] = True
    return {"name": "batch", "cat": "flow", "ph": ph, "id": int(fid),
            "ts": ts_us, "pid": 0, "tid": lane, "bp": "e"}


def record_event(name: str, t_start: float, t_end: float,
                 category: str = "op", flow: Optional[int] = None,
                 lane: Optional[str] = None) -> None:
    """Append one chrome-trace complete event (timestamps from
    time.perf_counter()). Recorded while the profiler state is ``run``
    (op/graph events) — span events come in through :func:`span`, which
    also records under ``MXNET_TPU_OBS``."""
    if not _tracing:
        return
    _append_event(name, t_start, t_end, category, flow, lane)


def _append_event(name, t_start, t_end, category, flow, lane,
                  count_span: bool = False) -> None:
    listener = _span_listener
    if listener is not None and count_span:
        # outside _lock: the listener (flight recorder) may snapshot the
        # counter table, which takes this module's lock itself
        try:
            listener(name, t_start, t_end, category, lane)
        except Exception:                                  # noqa: BLE001
            pass
    with _lock:
        # authoritative re-check under the lock: a concurrent
        # set_state("stop") + dump() must not observe a half-recorded
        # tail growing behind the serialized payload
        if not (_tracing or (count_span and _spans_on)):
            return
        if len(_events) >= _MAX_EVENTS:
            _counters["profiler_events_dropped"] = \
                _counters.get("profiler_events_dropped", 0) + 1
            return
        lid = _lane_id_locked(lane) if lane is not None \
            else _current_lane_locked()
        ts = (t_start - _t0) * 1e6
        ev = {"name": name, "cat": category, "ph": "X", "ts": ts,
              "dur": (t_end - t_start) * 1e6, "pid": 0, "tid": lid}
        if flow is not None:
            ev["args"] = {"flow": int(flow)}
        _events.append(ev)
        if flow is not None:
            _events.append(_flow_event_locked(int(flow), ts, lid))
        if count_span:
            _counters["obs_spans"] = _counters.get("obs_spans", 0) + 1


# --------------------------------------------------------------- spans

# span-close listener (one consumer: the mx.obs.blackbox flight
# recorder). When set, span() stays LIVE even while chrome-trace span
# recording is off, so the recorder's bounded ring sees span closes
# without the trace buffer growing; when None (the default) the shared
# no-op fast path is untouched — the zero-cost contract holds.
_span_listener = None


def set_span_listener(fn) -> None:
    """Install (``None`` removes) a callback invoked on every span close
    as ``fn(name, t_start, t_end, category, lane)``. Exceptions are
    swallowed — telemetry must never fail the traced code."""
    global _span_listener
    _span_listener = fn


def blackbox():
    """THE flight-recorder gate: the ``mx.obs.blackbox`` module iff
    armed (``MXNET_TPU_OBS_BLACKBOX`` names a directory), else None.
    Every hook site (fit loop, checkpoint writer, pod coordinator,
    fault harness) routes through this one implementation so the
    zero-import discipline — the recorder module never loads when the
    knob is off, subprocess-proven by the CI ``multihost`` gate — is
    maintained in exactly one place. Lives here, next to
    :func:`set_span_listener` (the recorder's other hook), because
    this module is jax-free and already imported by every caller."""
    if not _config.get("MXNET_TPU_OBS_BLACKBOX"):
        return None
    from .obs import blackbox as _bb
    return _bb


def spans_enabled() -> bool:
    """Fast, lock-free: True when span() currently records (profiler
    running or ``MXNET_TPU_OBS`` on)."""
    return _spans_on


class _NoopSpan(object):
    """Shared disabled-mode span: zero allocations per use."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def mark_flow(self, fid):
        pass


_NOOP_SPAN = _NoopSpan()


class _Span(object):
    __slots__ = ("name", "category", "flow", "lane", "_t0")

    def __init__(self, name, category, flow, lane):
        self.name = name
        self.category = category
        self.flow = flow
        self.lane = lane
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _append_event(self.name, self._t0, time.perf_counter(),
                      self.category, self.flow, self.lane, count_span=True)
        return False

    def mark_flow(self, fid) -> None:
        """Emit an extra flow step bound to this span's lane at the
        current time (serve: one batch slice carries many request
        flows)."""
        if fid is None:
            return
        now = time.perf_counter()
        with _lock:
            if not _spans_on or len(_events) >= _MAX_EVENTS:
                return
            lid = _lane_id_locked(self.lane) if self.lane is not None \
                else _current_lane_locked()
            _events.append(_flow_event_locked(int(fid),
                                              (now - _t0) * 1e6, lid))


def record_span(name: str, t_start: float, t_end: float,
                category: str = "span", flow: Optional[int] = None,
                lane: Optional[str] = None) -> None:
    """Low-level span record for sites that time conditionally (e.g. the
    serve coalescer, which only emits when a batch actually formed).
    Same gating as :func:`span`."""
    if not _spans_on and _span_listener is None:
        return
    _append_event(name, t_start, t_end, category, flow, lane,
                  count_span=True)


def span(name: str, category: str = "span", flow: Optional[int] = None,
         lane: Optional[str] = None):
    """Context manager timing one pipeline stage into the trace.

    ``flow`` links this slice to the other slices of the same batch or
    request across lanes; ``lane`` overrides the thread's lane with a
    named track. No-op (shared singleton, zero allocations) unless
    :func:`spans_enabled` or a span listener (the flight recorder) is
    installed.
    """
    if not _spans_on and _span_listener is None:
        return _NOOP_SPAN
    return _Span(name, category, flow, lane)


# ------------------------------------------------------------- counters
# Always-on framework counters (compile-cache hits/misses and friends —
# the TPU twin of the reference engine's aggregate stats). Unlike trace
# events these are cheap enough to count unconditionally, so tests can
# assert e.g. "one compiled executable per trainer step after warmup"
# without enabling tracing.


def incr_counter(name: str, n: int = 1) -> None:
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def get_counter(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def counters() -> dict:
    """Snapshot of all counters."""
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        _counters.clear()


class counter_delta(object):
    """Context manager snapshotting the counter table so tests and benches
    can assert on the increments one region produced (``with
    counter_delta() as d: ...; d.get("loop_host_sync")``) without clearing
    the global registry under concurrent users."""

    def __enter__(self):
        self._snap = counters()
        return self

    def __exit__(self, *exc):
        return False

    def get(self, name: str) -> int:
        return get_counter(name) - self._snap.get(name, 0)

    def all(self) -> dict:
        now = counters()
        return {k: v - self._snap.get(k, 0) for k, v in now.items()
                if v != self._snap.get(k, 0)}


# -------------------------------------------------------------- gauges
# Point-in-time values (queue depth, batch occupancy, ...) — unlike the
# monotonic counters above these are set, not accumulated. They share the
# counter registry's cheap always-on discipline so serving dashboards and
# tests can read them without enabling tracing.

_gauges: dict = {}


def set_gauge(name: str, value: float) -> None:
    with _lock:
        _gauges[name] = value


def get_gauge(name: str, default: float = 0.0) -> float:
    with _lock:
        return _gauges.get(name, default)


def gauges() -> dict:
    """Snapshot of all gauges."""
    with _lock:
        return dict(_gauges)


def reset_gauges() -> None:
    with _lock:
        _gauges.clear()


# ---------------------------------------------------------- histograms
# Bounded distribution summaries on fixed log-spaced buckets: O(number of
# buckets) memory at ANY observation volume, O(log buckets) record cost,
# quantile estimates within one bucket (factor 2^0.25 ≈ 19%) of the true
# percentile. The shared primitive behind serve latency percentiles and
# the obs bind-time accounting; exported in Prometheus histogram format
# by mx.obs.render_prometheus().

# 96 log-spaced bounds, 1e-5 .. ~1.4e7 (units are the caller's: seconds
# for latencies spans 10us..~160h, milliseconds for bind times spans
# 10ns..~4h)
_DEFAULT_BOUNDS = tuple(1e-5 * (2.0 ** (i / 4.0)) for i in range(96))


class Histogram(object):
    """Thread-safe fixed-bucket histogram (cumulative since last reset)."""

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_min", "_max",
                 "_hlock")

    def __init__(self, bounds=None):
        self.bounds = tuple(float(b) for b in (bounds or _DEFAULT_BOUNDS))
        assert all(a < b for a, b in zip(self.bounds, self.bounds[1:])), \
            "histogram bounds must be strictly increasing"
        # one overflow bucket past the last bound
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None
        self._hlock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.bounds, v)
        with self._hlock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def reset(self) -> None:
        with self._hlock:
            for i in range(len(self._counts)):
                self._counts[i] = 0
            self._sum = 0.0
            self._count = 0
            self._min = None
            self._max = None

    def snapshot(self) -> dict:
        """Consistent copy: {bounds, counts, sum, count, min, max}."""
        with self._hlock:
            return {"bounds": self.bounds, "counts": list(self._counts),
                    "sum": self._sum, "count": self._count,
                    "min": self._min, "max": self._max}

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0..1): linear interpolation inside the
        bucket holding the target rank; None while empty. Off by at most
        one bucket from the exact order statistic."""
        snap = self.snapshot()
        return _snapshot_quantile(snap, q)

    def quantiles(self, qs) -> List[Optional[float]]:
        snap = self.snapshot()
        return [_snapshot_quantile(snap, q) for q in qs]


def _snapshot_quantile(snap: dict, q: float) -> Optional[float]:
    count = snap["count"]
    if count == 0:
        return None
    q = min(max(float(q), 0.0), 1.0)
    target = q * count
    bounds, counts = snap["bounds"], snap["counts"]
    cum = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        prev_cum = cum
        cum += c
        if cum >= target:
            lo = bounds[i - 1] if i > 0 else max(
                0.0, snap["min"] if snap["min"] is not None else 0.0)
            hi = bounds[i] if i < len(bounds) else \
                (snap["max"] if snap["max"] is not None else bounds[-1])
            lo = max(lo, snap["min"]) if snap["min"] is not None else lo
            hi = min(hi, snap["max"]) if snap["max"] is not None else hi
            if hi <= lo:
                return lo
            frac = (target - prev_cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
    return snap["max"]


_histograms: Dict[str, Histogram] = {}


def histogram(name: str, bounds=None) -> Histogram:
    """Get-or-create the registry histogram ``name`` (shared across the
    process, like counters/gauges)."""
    with _lock:
        h = _histograms.get(name)
        if h is None:
            h = Histogram(bounds)
            _histograms[name] = h
        return h


def observe(name: str, value: float) -> None:
    """Record one observation into the registry histogram ``name``."""
    histogram(name).observe(value)


def histograms() -> Dict[str, Histogram]:
    """Snapshot of the histogram registry (name -> Histogram)."""
    with _lock:
        return dict(_histograms)


def reset_histograms() -> None:
    with _lock:
        for h in _histograms.values():
            h.reset()


class record(object):
    """Context manager: time a region into the profile."""

    def __init__(self, name: str, category: str = "region"):
        self.name = name
        self.category = category

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        record_event(self.name, self._t, time.perf_counter(),
                     self.category)
        return False


# serializes the file write of dump() without holding the hot-path
# _lock across disk I/O: two concurrent dump() calls to one filename
# must not interleave their buffered writes into unparseable JSON
_dump_lock = threading.Lock()


def dump(finished: bool = True) -> str:
    """Write the chrome-trace JSON; returns the path (reference:
    profiler.py:64 dump_profile -> engine Profiler::DumpProfile,
    src/engine/profiler.cc:127-179). The payload AND the target filename
    are captured under the lock (so a concurrent ``set_config`` swaps
    cleanly between dumps), and the write itself is serialized under a
    separate dump lock (so concurrent dumps cannot interleave)."""
    with _dump_lock:
        return _dump_locked(finished)


def _dump_locked(finished: bool) -> str:
    with _lock:
        events = list(_events)
        # lane-name metadata first (only for lanes that actually appear)
        # so every used tid renders under its registered name
        used = {e.get("tid") for e in events}
        meta = []
        for name, lid in sorted(_lanes.items(), key=lambda kv: kv[1]):
            if lid not in used:
                continue
            meta.append({"name": "thread_name", "ph": "M", "pid": 0,
                         "tid": lid, "args": {"name": name}})
            meta.append({"name": "thread_sort_index", "ph": "M", "pid": 0,
                         "tid": lid, "args": {"sort_index": lid}})
        payload = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        path = _filename
        if finished:
            _events.clear()
            _flows_seen.clear()
    if path == "profile.json":
        # shared-filesystem pods: every host dumping the DEFAULT
        # filename would clobber the others' traces — suffix the pod
        # rank (a pure state probe; an explicit set_config() filename
        # is the user's choice and is respected as-is)
        try:
            from .checkpoint.format import pod_info
            prank, pworld = pod_info()
        except Exception:                                  # noqa: BLE001
            prank, pworld = 0, 1
        if pworld > 1:
            path = "profile-p%d.json" % prank
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


# reference-compatible names
profiler_set_config = set_config
profiler_set_state = set_state
dump_profile = dump


# ------------------------------------------------------------- XLA layer


def start_xla_trace(logdir: str) -> None:
    """Start a jax/XLA device trace (TensorBoard format)."""
    import jax
    jax.profiler.start_trace(logdir)


def stop_xla_trace() -> None:
    import jax
    jax.profiler.stop_trace()


# keep the cached span flag honest under runtime knob flips
def _on_obs_knob(_value) -> None:
    with _lock:
        _recompute_enabled_locked()


_config.on_change("MXNET_TPU_OBS", _on_obs_knob)
with _lock:
    _recompute_enabled_locked()

"""``mx.profiler`` — execution tracing.

Reference: ``python/mxnet/profiler.py`` (profiler_set_config:27,
profiler_set_state:48, dump_profile:64) writing the chrome://tracing JSON
the engine emits in ``src/engine/profiler.cc:127-179``.

Two layers here:

* A framework-level event recorder: while the state is ``run``, every
  imperative op dispatch and every executor graph launch logs a
  chrome-trace complete event (synchronized — the op is blocked on so the
  duration is real device time, the profiler twin of the reference's
  engine sync mode). ``dump_profile()`` writes the standard
  ``{"traceEvents": [...]}`` JSON loadable in chrome://tracing / Perfetto.
* The XLA-level profiler: ``start_xla_trace(logdir)`` /
  ``stop_xla_trace()`` wrap ``jax.profiler`` for TensorBoard-grade HLO
  timelines on real hardware.

Counters/gauges are a third, always-on layer (string-keyed, thread-safe)
used by subsystems to make their hot-path invariants assertable. The
checkpoint subsystem's family (docs/architecture/checkpoint.md):
``ckpt_block_us`` (training-thread time spent in snapshot+submit — the
number that must stay small) vs ``ckpt_write_us`` (background
serialization+fsync time), ``ckpt_saved`` / ``ckpt_bytes`` /
``ckpt_save_async`` / ``ckpt_save_sync``, ``ckpt_backpressure_wait``
(writer queue was full at submit), ``ckpt_write_failed``,
``ckpt_load_ok`` / ``ckpt_load_fallback`` (corrupt candidate skipped),
``ckpt_gc_removed``, ``ckpt_sigterm``, and gauges ``ckpt_queue_depth``,
``ckpt_last_block_ms``, ``ckpt_last_write_ms``.
"""
from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

__all__ = [
    "profiler_set_config", "profiler_set_state", "dump_profile",
    "set_config", "set_state", "dump", "pause", "resume",
    "start_xla_trace", "stop_xla_trace", "record_event", "state",
    "incr_counter", "get_counter", "counters", "reset_counters",
    "counter_delta",
    "set_gauge", "get_gauge", "gauges", "reset_gauges",
]

_lock = threading.Lock()
_state = "stop"
_filename = "profile.json"
_events: List[dict] = []
_counters: dict = {}
_t0 = time.perf_counter()


def state() -> str:
    return _state


def set_config(filename: str = "profile.json", profile_all: bool = True,
               **_ignored) -> None:
    """(reference: profiler.py:27 profiler_set_config — mode knobs beyond
    the filename collapse: there is no per-subsystem engine here)."""
    global _filename
    _filename = filename


def set_state(st: str = "stop") -> None:
    """'run' starts recording, 'stop' stops (reference: profiler.py:48)."""
    global _state
    assert st in ("run", "stop"), st
    _state = st


def pause() -> None:
    set_state("stop")


def resume() -> None:
    set_state("run")


def record_event(name: str, t_start: float, t_end: float,
                 category: str = "op") -> None:
    """Append one chrome-trace complete event (timestamps from
    time.perf_counter())."""
    if _state != "run":
        return
    with _lock:
        _events.append({
            "name": name, "cat": category, "ph": "X",
            "ts": (t_start - _t0) * 1e6, "dur": (t_end - t_start) * 1e6,
            "pid": 0, "tid": threading.get_ident() % 100000,
        })


# ------------------------------------------------------------- counters
# Always-on framework counters (compile-cache hits/misses and friends —
# the TPU twin of the reference engine's aggregate stats). Unlike trace
# events these are cheap enough to count unconditionally, so tests can
# assert e.g. "one compiled executable per trainer step after warmup"
# without enabling tracing.


def incr_counter(name: str, n: int = 1) -> None:
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def get_counter(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def counters() -> dict:
    """Snapshot of all counters."""
    with _lock:
        return dict(_counters)


def reset_counters() -> None:
    with _lock:
        _counters.clear()


class counter_delta(object):
    """Context manager snapshotting the counter table so tests and benches
    can assert on the increments one region produced (``with
    counter_delta() as d: ...; d.get("loop_host_sync")``) without clearing
    the global registry under concurrent users."""

    def __enter__(self):
        self._snap = counters()
        return self

    def __exit__(self, *exc):
        return False

    def get(self, name: str) -> int:
        return get_counter(name) - self._snap.get(name, 0)

    def all(self) -> dict:
        now = counters()
        return {k: v - self._snap.get(k, 0) for k, v in now.items()
                if v != self._snap.get(k, 0)}


# -------------------------------------------------------------- gauges
# Point-in-time values (queue depth, batch occupancy, ...) — unlike the
# monotonic counters above these are set, not accumulated. They share the
# counter registry's cheap always-on discipline so serving dashboards and
# tests can read them without enabling tracing.

_gauges: dict = {}


def set_gauge(name: str, value: float) -> None:
    with _lock:
        _gauges[name] = value


def get_gauge(name: str, default: float = 0.0) -> float:
    with _lock:
        return _gauges.get(name, default)


def gauges() -> dict:
    """Snapshot of all gauges."""
    with _lock:
        return dict(_gauges)


def reset_gauges() -> None:
    with _lock:
        _gauges.clear()


class record(object):
    """Context manager: time a region into the profile."""

    def __init__(self, name: str, category: str = "region"):
        self.name = name
        self.category = category

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        record_event(self.name, self._t, time.perf_counter(),
                     self.category)
        return False


def dump(finished: bool = True) -> str:
    """Write the chrome-trace JSON; returns the path (reference:
    profiler.py:64 dump_profile -> engine Profiler::DumpProfile,
    src/engine/profiler.cc:127-179)."""
    with _lock:
        payload = {"traceEvents": list(_events),
                   "displayTimeUnit": "ms"}
        if finished:
            _events.clear()
    with open(_filename, "w") as f:
        json.dump(payload, f)
    return _filename


# reference-compatible names
profiler_set_config = set_config
profiler_set_state = set_state
dump_profile = dump


# ------------------------------------------------------------- XLA layer


def start_xla_trace(logdir: str) -> None:
    """Start a jax/XLA device trace (TensorBoard format)."""
    import jax
    jax.profiler.start_trace(logdir)


def stop_xla_trace() -> None:
    import jax
    jax.profiler.stop_trace()

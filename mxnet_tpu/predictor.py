"""``mx.predictor`` — the minimal deployment / inference API.

Reference: the C predict API (``include/mxnet/c_predict_api.h:77-178``,
impl ``src/c_api/c_predict_api.cc``): load a symbol JSON + param blob,
bind a forward-only executor, then ``SetInput -> Forward -> GetOutput``.
The amalgamation builds ship only this path (SURVEY.md §2.19).

TPU-native form: the "minimal runtime" is one jitted XLA program with
frozen weights — ``Predictor`` binds a forward-only Executor (no gradient
graph), device-puts the params once, and every ``forward`` is a single
cached-compile call. ``reshape`` rebinds for a new input geometry the way
``MXPredReshape`` does.

This is the single-request surface. For concurrent traffic, wrap it in
``mx.serve.InferenceServer`` (docs/architecture/serving.md): requests
coalesce into bucket-padded micro-batches and a finite executable set
serves arbitrary load with zero steady-state recompiles.
"""
from __future__ import annotations

import io
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import ndarray as nd
from .context import Context, cpu, current_context
from .symbol import load_json

__all__ = ["Predictor"]


class Predictor(object):
    """Forward-only model runner (reference: MXPredCreate semantics).

    Parameters
    ----------
    symbol_json : str
        Symbol JSON string (or a path ending in ``.json``).
    params : dict | str | bytes
        ``{name: array}`` dict, or a path / byte blob in the ``nd.save``
        container format with ``arg:``/``aux:`` prefixed keys (the
        checkpoint format ``model.save_checkpoint`` writes).
    input_shapes : dict | list of (name, shape)
        Shapes of every input that is not a parameter.
    ctx : Context, optional
    """

    def __init__(self, symbol_json, params, input_shapes,
                 ctx: Optional[Context] = None):
        self._ctx = ctx or current_context()
        if isinstance(symbol_json, str) and symbol_json.endswith(".json"):
            from . import filesystem as _fs
            with _fs.open_uri(symbol_json, "r") as path:
                with open(path) as f:
                    symbol_json = f.read()
        self._symbol = load_json(symbol_json)
        self._arg_params, self._aux_params = self._load_params(params)
        self._input_shapes = dict(input_shapes)
        self._inputs: Dict[str, nd.NDArray] = {}
        self._bind()

    @staticmethod
    def _load_params(params):
        """Split a params source into (arg_params, aux_params)
        (reference: c_predict_api.cc param-blob parsing of arg:/aux:
        prefixed names)."""
        if isinstance(params, (bytes, bytearray)):
            import tempfile
            with tempfile.NamedTemporaryFile(suffix=".params") as f:
                f.write(params)
                f.flush()
                loaded = nd.load(f.name)
        elif isinstance(params, str):
            loaded = nd.load(params)
        else:
            loaded = {k: (v if isinstance(v, nd.NDArray) else nd.array(v))
                      for k, v in params.items()}
        arg_params, aux_params = {}, {}
        for k, v in loaded.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        return arg_params, aux_params

    def _bind(self):
        sym = self._symbol
        # args that are neither params nor declared inputs are zero-filled
        # if their shape infers (checkpoints keep the loss head, so e.g.
        # softmax_label rides along; forward ignores it — same situation
        # the reference predict API handles for deployed training symbols)
        missing = [n for n in sym.list_arguments()
                   if n not in self._arg_params
                   and n not in self._input_shapes]
        hard = [n for n in missing if not n.endswith("label")]
        if hard:
            raise ValueError(
                "Predictor: arguments %s are neither params nor declared "
                "inputs" % hard)
        shapes = dict(self._input_shapes)
        shapes.update({k: v.shape for k, v in self._arg_params.items()
                       if k in sym.list_arguments()})
        try:
            arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
        except Exception as exc:
            raise ValueError(
                "Predictor: cannot infer shapes%s: %s"
                % (" (arguments %s are neither params nor declared inputs)"
                   % missing if missing else "", exc)) from None
        args = {}
        for name, shp in zip(sym.list_arguments(), arg_shapes):
            if name in self._arg_params:
                args[name] = self._arg_params[name].copyto(self._ctx)
            else:
                args[name] = nd.zeros(shp, ctx=self._ctx)
        aux = {}
        for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
            aux[name] = (self._aux_params[name].copyto(self._ctx)
                         if name in self._aux_params
                         else nd.zeros(shp, ctx=self._ctx))
        self._exec = sym.bind(self._ctx, args=args, aux_states=aux,
                              grad_req="null")

    # ------------------------------------------------------------ predict
    def set_input(self, name: str, value) -> "Predictor":
        """(reference: MXPredSetInput)."""
        if name not in self._input_shapes:
            raise KeyError("unknown input %r (declared: %s)"
                           % (name, sorted(self._input_shapes)))
        arr = value if isinstance(value, nd.NDArray) else nd.array(value)
        want = tuple(self._input_shapes[name])
        if tuple(arr.shape) != want:
            raise ValueError("input %r has shape %s, predictor bound for %s"
                             " (use reshape())" % (name, arr.shape, want))
        self._exec.arg_dict[name][:] = arr
        return self

    def forward(self, **inputs) -> List[nd.NDArray]:
        """Run the forward program; keyword inputs are a shorthand for
        set_input (reference: MXPredForward)."""
        for k, v in inputs.items():
            self.set_input(k, v)
        return self._exec.forward(is_train=False)

    def get_output(self, index: int) -> nd.NDArray:
        """(reference: MXPredGetOutput)."""
        return self._exec.outputs[index]

    @property
    def outputs(self) -> List[nd.NDArray]:
        return self._exec.outputs

    def reshape(self, input_shapes) -> "Predictor":
        """Rebind for new input geometry (reference: MXPredReshape)."""
        self._input_shapes = dict(input_shapes)
        self._bind()
        return self

    # ------------------------------------------------------------ export
    def export(self, path: str, platforms: Optional[Sequence[str]] = None
               ) -> str:
        """Serialize the forward program as a self-contained AOT artifact
        (StableHLO via ``jax.export``) + frozen weights + manifest, in one
        zip. The artifact runs WITHOUT this framework — any jax install
        can execute it via ``tools/predict_exported.py`` (~60 lines, no
        mxnet_tpu import). This is the deployment-export capability of the
        reference's amalgamation predict build (amalgamation/Makefile,
        c_predict_api.h:77-178): a single shippable file containing the
        whole model.

        ``platforms`` pins the lowering targets (e.g. ``["cpu", "tpu"]``);
        default is the current backend.
        """
        import json
        import zipfile

        import jax
        import jax.numpy as jnp
        from jax import export as jexport

        from .executor import graph_function

        sym = self._symbol
        fn = graph_function(sym)
        arg_names = list(sym.list_arguments())
        aux_names = list(sym.list_auxiliary_states())
        input_names = [n for n in arg_names if n in self._input_shapes]
        weight_names = [n for n in arg_names if n not in self._input_shapes]

        weights = {n: np.asarray(self._exec.arg_dict[n].asnumpy())
                   for n in weight_names}
        aux_vals = {n: np.asarray(self._exec.aux_dict[n].asnumpy())
                    for n in aux_names}

        def pure(*flat):
            args = dict(zip(weight_names, flat[:len(weight_names)]))
            args.update(zip(input_names, flat[len(weight_names):]))
            aux = {n: jnp.asarray(aux_vals[n]) for n in aux_names}
            outs, _ = fn(args, aux, jax.random.PRNGKey(0), False)
            return tuple(outs)

        flat_sds = [jax.ShapeDtypeStruct(weights[n].shape,
                                         weights[n].dtype)
                    for n in weight_names]
        flat_sds += [jax.ShapeDtypeStruct(
            tuple(self._input_shapes[n]),
            np.asarray(self._exec.arg_dict[n].asnumpy()).dtype)
            for n in input_names]
        kwargs = {}
        if platforms is not None:
            kwargs["platforms"] = list(platforms)
        exported = jexport.export(jax.jit(pure), **kwargs)(*flat_sds)

        manifest = {
            "format": "mxnet_tpu.exported/1",
            "weights": weight_names,
            "inputs": input_names,
            "input_shapes": {n: list(self._input_shapes[n])
                             for n in input_names},
            "num_outputs": len(sym.list_outputs()),
            "platforms": list(exported.platforms),
        }
        from . import filesystem as _fs
        from .checkpoint.atomic import atomic_open
        with _fs.open_uri(path, "w") as local:   # s3://, hdfs://, local
            # atomic: the zip grows through a fsynced temp file renamed
            # over the target, so a crash mid-export can't leave a torn
            # (half-written central directory) artifact at the final name
            with atomic_open(local, "wb") as fobj:
                with zipfile.ZipFile(fobj, "w") as z:
                    z.writestr("manifest.json",
                               json.dumps(manifest, indent=1))
                    z.writestr("program.stablehlo", exported.serialize())
                    buf = io.BytesIO()
                    np.savez(buf, **weights)
                    z.writestr("weights.npz", buf.getvalue())
        return path

    # ------------------------------------------------------------ loaders
    @classmethod
    def from_checkpoint(cls, prefix: str, epoch: int, input_shapes,
                        ctx: Optional[Context] = None) -> "Predictor":
        """Load ``prefix-symbol.json`` + ``prefix-%04d.params`` (the
        Module/model checkpoint layout, reference model.py:370). The
        prefix may be a remote URI (s3://...) — both files stage through
        mx.filesystem."""
        return cls("%s-symbol.json" % prefix,
                   "%s-%04d.params" % (prefix, epoch),
                   input_shapes, ctx=ctx)
